"""mxtsan concurrency sanitizer (the ISSUE-9 acceptance gates).

Seeded defect fixtures — a forced A->B / B->A lock-order inversion, an
unsynchronized shared-dict write race, a leaked unjoined thread, a
blocking sleep under a contended lock, a thread outliving its owner's
close() — each asserting the finding names the exact locks/objects,
threads, and ``file:line`` sites.  Plus: the zero-overhead contract
(flag unset -> the shims ARE the plain threading objects), the
MXNET_TSAN_RAISE escalation, the concurrency AST lints, regression
locks for the two real races the sanitizer surfaced (router slot
bookkeeping, supervisor stats counters), and the zero-false-positive
gate over a tier-1-representative workload (fit step, serving
round-trip, dist push/pull) with the sanitizer on.
"""
import os
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import analysis, io, sym
from incubator_mxnet_tpu.analysis import locks as alocks
from incubator_mxnet_tpu.analysis import tsan
from incubator_mxnet_tpu.base import MXNetError


@pytest.fixture
def tsan_on():
    """Sanitizer on for this test, restored (and wiped) afterwards."""
    was = tsan.enabled()
    tsan.reset()
    tsan.enable()
    yield tsan
    if not was:
        tsan.disable()
    tsan.reset()


def _by_code(code):
    return [f for f in tsan.findings() if f.code == code]


# -- zero-overhead contract ---------------------------------------------------

def test_shims_are_plain_threading_objects_when_off():
    """With the sanitizer off, make_lock/make_rlock/make_condition hand
    back the stock threading primitives — not wrappers."""
    if tsan.enabled():   # the flag is on under the parity tsan stage
        pytest.skip("MXNET_TSAN=1 in this process")
    lk = alocks.make_lock("x")
    assert type(lk) is type(threading.Lock())
    rk = alocks.make_rlock("x")
    assert type(rk) is type(threading.RLock())
    cond = alocks.make_condition(name="x")
    assert isinstance(cond, threading.Condition)
    assert type(cond._lock) is type(threading.RLock())
    d = tsan.shared_dict("x")
    assert type(d) is dict
    class Obj:
        pass
    o = Obj()
    assert tsan.instrument(o, "x") is o and type(o) is Obj


# -- seeded defect fixtures ---------------------------------------------------

def test_lock_order_inversion_fixture(tsan_on):
    """A->B in one thread, B->A in another: the sanitizer reports the
    potential deadlock naming both locks, both threads, and the two
    acquisition sites — before anything hangs."""
    a = alocks.make_lock("fixture.A")
    b = alocks.make_lock("fixture.B")

    def forward():
        with a:
            with b:       # A -> B
                pass

    def backward():
        with b:
            with a:       # B -> A: closes the cycle
                pass

    t1 = threading.Thread(target=forward, name="fix-forward")
    t1.start(); t1.join(5)
    t2 = threading.Thread(target=backward, name="fix-backward")
    t2.start(); t2.join(5)

    found = _by_code("lock-order-inversion")
    assert found, tsan.findings()
    msg = found[0].message
    assert "fixture.A" in msg and "fixture.B" in msg
    assert "fix-forward" in msg and "fix-backward" in msg
    # both with-blocks above are named by file:line in this test file
    assert msg.count("test_tsan.py") >= 2
    assert found[0].severity == "error"
    # the graph artifact carries both edges
    graph = tsan.lock_graph()
    pairs = {(e["from"], e["to"]) for e in graph["edges"]}
    assert ("fixture.A", "fixture.B") in pairs
    assert ("fixture.B", "fixture.A") in pairs


def test_lock_order_raise_escalation(tsan_on):
    """MXNET_TSAN_RAISE=1 turns the inversion into an MXNetError at the
    acquisition site, with the lock released behind it."""
    os.environ["MXNET_TSAN_RAISE"] = "1"
    try:
        a = alocks.make_lock("raise.A")
        b = alocks.make_lock("raise.B")
        with a:
            with b:
                pass
        with b:
            with pytest.raises(MXNetError, match="raise.A"):
                with a:
                    pass
        # the failed acquisition did not leak the lock
        assert a.acquire(blocking=False)
        a.release()
    finally:
        os.environ.pop("MXNET_TSAN_RAISE", None)


def test_shared_dict_write_race_fixture(tsan_on):
    """Two threads writing one key with no common lock: attributed to
    both sites, both threads, named state."""
    d = tsan.shared_dict("fixture.table")

    def writer():
        d["hot"] = 1      # no lock held

    t = threading.Thread(target=writer, name="fix-writer")
    t.start(); t.join(5)
    d["hot"] = 2          # MainThread, no lock held

    found = _by_code("shared-state-race")
    assert found, tsan.findings()
    msg = found[0].message
    assert "fixture.table['hot']" in msg
    assert "write/write" in msg
    assert "fix-writer" in msg and "MainThread" in msg
    assert msg.count("test_tsan.py") >= 2


def test_shared_dict_guarded_writes_are_clean(tsan_on):
    """The same access pattern under a common lock produces nothing."""
    lk = alocks.make_lock("fixture.guard")
    d = tsan.shared_dict("fixture.guarded")

    def writer():
        with lk:
            d["hot"] = 1

    t = threading.Thread(target=writer, name="fix-guarded-writer")
    t.start(); t.join(5)
    with lk:
        d["hot"] = 2
        assert d["hot"] == 2
    assert not _by_code("shared-state-race"), tsan.findings()


def test_instrumented_attribute_race_fixture(tsan_on):
    """Attribute writes on a registered object race across threads."""
    class Stats:
        def __init__(self):
            self.count = 0

    s = tsan.instrument(Stats(), "fixture.stats")

    def bump():
        s.count += 1

    t = threading.Thread(target=bump, name="fix-bumper")
    t.start(); t.join(5)
    s.count += 1
    found = _by_code("shared-state-race")
    assert found, tsan.findings()
    assert "fixture.stats['count']" in found[0].message


def test_leaked_thread_fixture(tsan_on):
    """A started, never-joined non-daemon thread is reported with its
    creation site."""
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, name="fix-leaker",
                         daemon=False)
    t.start()
    try:
        found = _by_code("leaked-thread")
        assert found, tsan.findings()
        msg = found[0].message
        assert "fix-leaker" in msg and "test_tsan.py" in msg
    finally:
        stop.set()
        t.join(5)


def test_blocking_sleep_under_contended_lock_fixture(tsan_on):
    """time.sleep while holding a lock another thread uses: flagged with
    the lock name and the blocking site."""
    lk = alocks.make_lock("fixture.hot-lock")

    def toucher():
        with lk:
            pass

    t = threading.Thread(target=toucher, name="fix-toucher")
    t.start(); t.join(5)
    with lk:                      # now contended (two threads used it)
        time.sleep(0.005)
    found = _by_code("blocking-under-lock")
    assert found, tsan.findings()
    msg = found[0].message
    assert "fixture.hot-lock" in msg and "time.sleep" in msg
    assert "test_tsan.py" in msg


def test_thread_outlives_close_fixture(tsan_on):
    """The audited close-path join flags a worker that survives it."""
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, name="fix-wedged",
                         daemon=True)
    t.start()
    try:
        assert tsan.join_thread(t, 0.05, owner="FixtureOwner") is False
        found = _by_code("thread-outlives-close")
        assert found, tsan.findings()
        msg = found[0].message
        assert "fix-wedged" in msg and "FixtureOwner" in msg
    finally:
        stop.set()
        t.join(5)


# -- AST lints (the static half) ---------------------------------------------

def test_concurrency_ast_lints():
    src = '''
import threading, time
lock = threading.Lock()

class Pool:
    def __init__(self):
        self.t = threading.Thread(target=print)   # unnamed + unjoined
        self.t.start()

def drain():
    lock.acquire()
    with lock:
        time.sleep(0.5)
    lock.release()
'''
    rep = analysis.check_source(src, filename="fixture.py")
    codes = {f.code for f in rep}
    assert "unnamed-thread" in codes
    assert "unjoined-thread-in-init" in codes
    assert "bare-acquire" in codes
    assert "sleep-under-lock" in codes
    # named thread + lifecycle method + with-scope: all clean
    clean = '''
import threading, time

class Pool:
    def __init__(self):
        self.t = threading.Thread(target=print, name="mx-pool-worker")
        self.t.start()

    def close(self):
        self.t.join(timeout=5)

def drain(lock):
    with lock:
        pass
    time.sleep(0.5)
'''
    rep = analysis.check_source(clean, filename="clean.py")
    from incubator_mxnet_tpu.analysis.source_lint import CONCURRENCY_CODES
    assert not [f for f in rep if f.code in CONCURRENCY_CODES], list(rep)


def test_package_is_clean_under_concurrency_lints():
    """The sweep the parity tsan stage gates on: zero findings over the
    package source."""
    from incubator_mxnet_tpu.analysis.source_lint import CONCURRENCY_CODES
    pkg = os.path.dirname(analysis.__file__)
    pkg = os.path.dirname(pkg)   # incubator_mxnet_tpu/
    bad = []
    for root, dirs, files in os.walk(pkg):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            rep = analysis.check_source_file(os.path.join(root, f))
            bad.extend(f2 for f2 in rep if f2.code in CONCURRENCY_CODES)
    assert not bad, "\n".join(f.format() for f in bad)


# -- regression locks for the races the sanitizer surfaced -------------------

def _mlp_net():
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=8, name="fc0")
    net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=3, name="head")
    return sym.SoftmaxOutput(net, name="softmax")


def _served_model(name, batch=4):
    np.random.seed(0)
    net = _mlp_net()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (batch, 6))],
             label_shapes=[io.DataDesc("softmax_label", (batch,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    args, auxs = mod.get_params()
    return mx.serving.ServedModel(net, args, auxs,
                                  data_shapes=[("data", (1, 6))],
                                  buckets=(1, 2, 4), ctx=mx.cpu(),
                                  name=name)


def test_router_health_and_dispatch_race_free(tsan_on):
    """Regression for the health-loop race: slot bookkeeping (probes,
    state, last_ok) is now written under the router lock, so a fast
    health loop concurrent with dispatch threads and a weight-state
    flip produces ZERO shared-state findings on the slot objects."""
    from incubator_mxnet_tpu.serving.replica import LocalReplica
    from incubator_mxnet_tpu.serving.router import ReplicaRouter

    model = _served_model("tsan-router")
    model.warmup()
    router = ReplicaRouter(
        [LocalReplica(model, replica_id="r0")],
        name="tsan-router", health_interval_s=0.01, deepcheck_every=3)
    try:
        x = np.random.randn(2, 6).astype(np.float32)
        stop = threading.Event()

        def client():
            while not stop.is_set():
                router.predict({"data": x}, timeout_ms=2000)

        threads = [threading.Thread(target=client,
                                    name=f"tsan-client-{i}")
                   for i in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.4)   # dozens of health probes + dispatches overlap
        stop.set()
        for t in threads:
            t.join(10)
    finally:
        router.shutdown(drain=False)
    races = [f for f in _by_code("shared-state-race")
             if "router" in f.message]
    assert not races, "\n".join(f.format() for f in races)
    assert not _by_code("lock-order-inversion"), tsan.findings()


def test_supervisor_stats_race_free(tsan_on):
    """Regression for the stats-counter race: every `_stats` update now
    holds the view lock, so heartbeat-thread counters concurrent with
    fit-thread collectives produce zero findings."""
    from incubator_mxnet_tpu.resilience.supervisor import JobSupervisor

    sup = JobSupervisor(rank=0, num_workers=2)
    view = {"epoch": 0, "alive": [0, 1], "dead": [], "age": {},
            "steps": {0: 1, 1: 1}, "ewma": {}}
    stop = threading.Event()

    def beat():
        while not stop.is_set():
            sup._on_view(view)
            with sup._view_lock:
                sup._stats["heartbeats"] += 1

    t = threading.Thread(target=beat, name="tsan-hb")
    t.start()
    for _ in range(50):
        sup.collective("noop", lambda: 1)
        sup.record_step(0.001)
    stop.set()
    t.join(10)
    sup.stop()
    races = [f for f in _by_code("shared-state-race")
             if "supervisor" in f.message]
    assert not races, "\n".join(f.format() for f in races)
    assert sup.stats()["collectives"] == 50


# -- the zero-false-positive gate ---------------------------------------------

def test_zero_false_positives_on_tier1_workload(tsan_on):
    """A tier-1-representative workload under the sanitizer — a fit
    step, a serving round-trip through the micro-batcher, and a dist
    push/pull over the socket server — must produce ZERO findings: the
    sanitizer earns its place only if a clean system reads clean."""
    # 1. fit step (module data plane, engine, compile cache, storage)
    np.random.seed(0)
    X = np.random.randn(64, 6).astype(np.float32)
    y = np.random.randint(0, 3, 64)
    train = io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp_net(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(), num_epoch=1)

    # 2. serving round-trip (batcher worker + metrics + breaker)
    model = _served_model("tsan-gate")
    server = mx.serving.ModelServer()
    server.load_model("tsan-gate", model=model)
    outs = [server.submit("tsan-gate",
                          {"data": np.random.randn(2, 6).astype(
                              np.float32)})
            for _ in range(8)]
    for f in outs:
        f.result(30)
    server.shutdown(drain=True)

    # 3. dist push/pull (transport, parameter server, membership-free)
    from incubator_mxnet_tpu.dist.server import ParameterServer
    from incubator_mxnet_tpu.dist.kvstore_dist import KVStoreDist
    from incubator_mxnet_tpu import nd

    psrv = ParameterServer(num_workers=1).start()
    old = {k: os.environ.get(k) for k in
           ("DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT", "DMLC_RANK",
            "DMLC_NUM_WORKER")}
    os.environ.update(DMLC_PS_ROOT_URI="127.0.0.1",
                      DMLC_PS_ROOT_PORT=str(psrv.port),
                      DMLC_RANK="0", DMLC_NUM_WORKER="1")
    try:
        kv = KVStoreDist("dist_async")
        kv.init("w", nd.zeros((4,)))
        kv.push("w", nd.ones((4,)))
        out = nd.zeros((4,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 1.0)
        kv.close()
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        psrv.shutdown()

    found = tsan.findings()
    assert not found, "\n".join(f.format() for f in found)


def test_findings_flow_into_runtime_report(tsan_on):
    """tsan findings ride the same Report machinery as every other
    analysis pass."""
    d = tsan.shared_dict("report.state")
    t = threading.Thread(target=lambda: d.__setitem__("k", 1),
                         name="report-writer")
    t.start(); t.join(5)
    d["k"] = 2
    rep = analysis.runtime_report()
    assert any(f.code == "shared-state-race" for f in rep), list(rep)


def test_dump_artifact_roundtrip(tsan_on, tmp_path):
    """The MXNET_TSAN_LOG artifact carries findings + the lock graph,
    and mxlint --tsan-report renders it."""
    a = alocks.make_lock("dump.A")
    b = alocks.make_lock("dump.B")
    with a:
        with b:
            pass
    path = tmp_path / "tsan.json"
    payload = tsan.dump(str(path))
    assert path.exists()
    names = {e["name"] for e in payload["lock_graph"]["locks"]}
    assert {"dump.A", "dump.B"} <= names
    pairs = {(e["from"], e["to"]) for e in payload["lock_graph"]["edges"]}
    assert ("dump.A", "dump.B") in pairs

    import subprocess, sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "mxlint.py"),
         "--tsan-report", str(path), "--json"],
        capture_output=True, text=True, timeout=300)
    import json
    summary = json.loads(out.stdout)
    assert summary["runtime"]["dumps"] == 1
    assert summary["runtime"]["lock_graph"]["edges"]
