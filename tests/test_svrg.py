"""SVRG tests (reference tests/python/unittest/test_contrib_svrg_module.py
strategy: converges, and the variance-reduced gradient at the snapshot
equals the plain gradient)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.contrib.svrg_optimization import SVRGModule


def _problem():
    rng = np.random.RandomState(0)
    X = rng.randn(128, 6).astype("f4")
    W = rng.randn(6, 1).astype("f4")
    Y = (X @ W + 0.05 * rng.randn(128, 1)).astype("f4")
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=1, name="fc")
    out = mx.sym.LinearRegressionOutput(out, name="lro")
    it = mx.io.NDArrayIter(X, Y, batch_size=32, label_name="lro_label")
    return out, it, X, Y


def test_svrg_converges():
    sym, it, X, Y = _problem()
    mod = SVRGModule(sym, label_names=("lro_label",), update_freq=2,
                     context=mx.cpu())
    mod.fit(it, num_epoch=25, eval_metric="mse", optimizer="sgd",
            optimizer_params={"learning_rate": 0.3,
                              "rescale_grad": 1.0 / 32})
    it.reset()
    score = dict(mod.score(it, mx.metric.MSE()))["mse"]
    assert score < 0.05, score


def test_svrg_estimator_unbiased_at_snapshot():
    """Right after a snapshot, g - g_snap + mu == mu + 0 when evaluated at
    w == w_snap with the same batch: the correction must vanish."""
    sym, it, X, Y = _problem()
    mod = SVRGModule(sym, label_names=("lro_label",), update_freq=1,
                     context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.0),))
    mod._take_snapshot(it)
    it.reset()
    batch = next(iter(it))
    mod.forward_backward(batch)
    live = {k: g.asnumpy().copy() for k, g in mod._live_grads().items()}
    snap = {k: g.asnumpy() for k, g in mod._grad_at_snapshot(batch).items()}
    for k in live:
        np.testing.assert_allclose(live[k], snap[k], rtol=1e-5, atol=1e-6)


def test_svrg_correction_is_not_plain_mu_after_update():
    """After one optimizer step away from the snapshot, g_live != g_snap,
    so the written gradient must differ from mu (guards against the
    aliasing bug where the live grads were read AFTER being overwritten
    by the snapshot pass)."""
    sym, it, X, Y = _problem()
    mod = SVRGModule(sym, label_names=("lro_label",), update_freq=1,
                     context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.05),))
    mod._take_snapshot(it)
    it.reset()
    batches = list(it)
    # one real step moves w away from w_snap
    mod.forward_backward(batches[0])
    mod.update()
    mod.forward_backward(batches[1])
    live = {k: g.copyto(g.context) for k, g in mod._live_grads().items()}
    snap = mod._grad_at_snapshot(batches[1])
    diff = sum(float(np.abs((live[k] - snap[k]).asnumpy()).sum())
               for k in live)
    assert diff > 1e-4, "live and snapshot grads identical: aliasing bug"
