"""Unified program cache (compile/): disk-tier round trips, corruption
safety, key discrimination (donation/dtype/graph), concurrent writers,
AOT warmup, and the checkpoint ``programs/`` payload.

The acceptance story: a SECOND process that builds the same programs
must perform zero XLA compilations — every executable loads from the
disk tier (serialized by the first process, CRC'd, atomically
published)."""
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import compile as mxc
from incubator_mxnet_tpu.compile import ProgramCache, cached_jit
from incubator_mxnet_tpu.compile.cache import _unframe

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _entry_files(root):
    from incubator_mxnet_tpu.compile.cache import FORMAT_VERSION
    vdir = os.path.join(str(root), "v%d" % FORMAT_VERSION)
    if not os.path.isdir(vdir):
        return []
    return sorted(os.path.join(vdir, f) for f in os.listdir(vdir)
                  if f.endswith(".xprog"))


def _fn(x, y):
    import jax.numpy as jnp
    return jnp.tanh(x @ y) + jnp.float32(1.0)


def test_disk_round_trip_bit_identical(tmp_path):
    """Compile once, then reload from disk in a FRESH wrapper (the
    in-memory tier gone, as after a process restart): zero compiles and
    bit-identical outputs."""
    a = np.random.RandomState(0).rand(8, 8).astype("f4")
    b = np.random.RandomState(1).rand(8, 8).astype("f4")

    c1 = cached_jit(_fn, graph_key="round-trip",
                    cache=ProgramCache(tmp_path))
    out1 = np.asarray(c1(a, b))
    assert c1.compile_count == 1 and c1.disk_hits == 0
    assert len(_entry_files(tmp_path)) == 1

    cache2 = ProgramCache(tmp_path)      # fresh memory tier
    c2 = cached_jit(_fn, graph_key="round-trip", cache=cache2)
    out2 = np.asarray(c2(a, b))
    assert c2.compile_count == 0, "second build must not compile"
    assert c2.disk_hits == 1
    assert cache2.counters["disk_hits"] == 1
    np.testing.assert_array_equal(out1, out2)   # bit-identical


def test_corrupt_and_torn_entries_fall_back(tmp_path):
    """A bit-flipped or truncated entry fails its CRC, is deleted, and
    the caller transparently recompiles."""
    a = np.ones((4, 4), "f4")
    c1 = cached_jit(_fn, graph_key="corrupt", cache=ProgramCache(tmp_path))
    want = np.asarray(c1(a, a))
    (path,) = _entry_files(tmp_path)

    # bit-flip mid-payload
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(blob))
    cache2 = ProgramCache(tmp_path)
    c2 = cached_jit(_fn, graph_key="corrupt", cache=cache2)
    np.testing.assert_array_equal(np.asarray(c2(a, a)), want)
    assert cache2.counters["corrupt"] == 1
    assert c2.compile_count == 1          # recompiled
    assert not os.path.exists(path) or _unframe(
        open(path, "rb").read()) is not None   # bad entry gone/replaced

    # torn write: truncate the (re-stored) entry
    (path,) = _entry_files(tmp_path)
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:len(blob) // 3])
    cache3 = ProgramCache(tmp_path)
    c3 = cached_jit(_fn, graph_key="corrupt", cache=cache3)
    np.testing.assert_array_equal(np.asarray(c3(a, a)), want)
    assert cache3.counters["corrupt"] == 1
    assert c3.compile_count == 1


def test_key_discriminates_donation_dtype_and_graph(tmp_path):
    """No false hits: donation spec, input dtype, and graph key all feed
    the entry key — a program compiled without donation (or at another
    dtype) must never satisfy a donating (or re-dtyped) lookup."""
    a32 = np.ones((4, 4), "f4")

    cache = ProgramCache(tmp_path)
    plain = cached_jit(_fn, graph_key="disc", cache=cache)
    plain(a32, a32)
    assert len(_entry_files(tmp_path)) == 1

    donating = cached_jit(_fn, donate_argnums=(0,), graph_key="disc",
                          cache=ProgramCache(tmp_path))
    import jax
    donating(jax.numpy.asarray(a32), a32)
    assert donating.disk_hits == 0 and donating.compile_count == 1
    assert len(_entry_files(tmp_path)) == 2   # distinct entry

    a16 = np.ones((4, 4), np.float16)
    redtyped = cached_jit(_fn, graph_key="disc",
                          cache=ProgramCache(tmp_path))
    redtyped(a16, a16)
    assert redtyped.disk_hits == 0 and redtyped.compile_count == 1
    assert len(_entry_files(tmp_path)) == 3

    other = cached_jit(_fn, graph_key="other-graph",
                       cache=ProgramCache(tmp_path))
    other(a32, a32)
    assert other.disk_hits == 0
    assert len(_entry_files(tmp_path)) == 4


def test_versioned_eviction(tmp_path):
    """Entries from another device topology / jax version are evicted at
    load, never deserialized."""
    a = np.ones((4, 4), "f4")
    c1 = cached_jit(_fn, graph_key="fp", cache=ProgramCache(tmp_path))
    c1(a, a)
    (path,) = _entry_files(tmp_path)
    raw = open(path, "rb").read()
    header, payload = _unframe(raw)
    header["fingerprint"] = "tpu|TPU v9|d4096|p512|jax=99.0"
    from incubator_mxnet_tpu.compile.cache import _frame
    with open(path, "wb") as f:
        f.write(_frame(header, payload))

    cache2 = ProgramCache(tmp_path)
    c2 = cached_jit(_fn, graph_key="fp", cache=cache2)
    c2(a, a)
    assert cache2.counters["evicted"] == 1
    assert c2.compile_count == 1


def test_concurrent_writers_do_not_clobber(tmp_path):
    """Racing writers of the same key (atomic-rename publication): the
    surviving entry must be whole and loadable."""
    a = np.ones((6, 6), "f4")
    errs = []

    def worker():
        try:
            c = cached_jit(_fn, graph_key="race",
                           cache=ProgramCache(tmp_path))
            c(a, a)
        except Exception as e:   # pragma: no cover - the assertion below
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    files = _entry_files(tmp_path)
    assert len(files) == 1
    assert _unframe(open(files[0], "rb").read()) is not None
    # and the published entry round-trips into a working executable
    cache2 = ProgramCache(tmp_path)
    c2 = cached_jit(_fn, graph_key="race", cache=cache2)
    c2(a, a)
    assert c2.disk_hits == 1 and c2.compile_count == 0


def test_export_and_source_payload(tmp_path):
    """export_to writes standard entries a read-only source can serve
    (the checkpoint programs/ payload mechanism) — the consumer has NO
    writable directory and still skips the compile."""
    a = np.ones((5, 5), "f4")
    c1 = cached_jit(_fn, graph_key="payload", cache=ProgramCache())
    c1(a, a)                      # memory-only compile (no disk tier)
    payload = tmp_path / "programs"
    assert c1.export_to(payload) == 1

    consumer = ProgramCache(sources=[str(payload)])
    c2 = cached_jit(_fn, graph_key="payload", cache=consumer)
    c2(a, a)
    assert c2.compile_count == 0 and c2.disk_hits == 1


def test_second_process_serving_ladder_zero_compiles(tmp_path):
    """The acceptance gate: a second process warming the same serving
    bucket ladder performs ZERO XLA compilations, and the recompile
    auditor records no post-warmup churn (every signature was declared
    by warmup)."""
    cache = str(tmp_path / "cache")
    script = (
        "import json\n"
        "from incubator_mxnet_tpu.compile.warmup import selftest\n"
        "from incubator_mxnet_tpu import analysis\n"
        "out = selftest(%r)\n"
        "out['churn_findings'] = len(analysis.recompile.findings())\n"
        "print(json.dumps(out))\n" % cache)
    results = []
    for _ in range(2):
        r = subprocess.run([sys.executable, "-c", script], cwd=REPO,
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        results.append(json.loads(r.stdout.strip().splitlines()[-1]))
    cold, warm = results
    assert cold["compiles"] == len(cold["buckets"])
    assert cold["churn_findings"] == 0
    assert warm["compiles"] == 0, warm       # certifiably zero compiles
    assert warm["disk_hits"] == len(warm["buckets"])
    assert warm["churn_findings"] == 0


def test_checkpoint_programs_payload_and_resume(tmp_path):
    """Module.fit(checkpoint_dir=) ships a programs/ payload; the resumed
    process's fused step loads its executable from it (zero compiles).
    Runs in subprocesses because the memory tier of THIS process would
    mask the disk hit."""
    ckpt = str(tmp_path / "ckpt")
    cache = str(tmp_path / "cache")
    script = r'''
import os, sys, json
os.environ["MXNET_PROGRAM_CACHE_DIR"] = %r
os.environ["MXNET_FUSED_STEP_BLOCK"] = "4"
import numpy as np
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import io, compile as mxc
np.random.seed(0); mx.random.seed(0)
X = np.random.rand(64, 16).astype("f4")
Y = np.random.randint(0, 4, 64).astype("f4")
it = io.NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")
data = mx.sym.Variable("data")
out = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
out = mx.sym.Activation(out, act_type="relu")
out = mx.sym.FullyConnected(out, num_hidden=4, name="fc2")
out = mx.sym.SoftmaxOutput(out, name="softmax")
mod = mx.mod.Module(out, label_names=("softmax_label",))
resume = os.path.isdir(os.path.join(%r, "programs"))
mod.fit(it, num_epoch=2 if resume else 1, optimizer="sgd",
        optimizer_params={"learning_rate": 0.1}, eval_metric="acc",
        checkpoint_dir=%r, checkpoint_period=8, resume=resume,
        kvstore=None)
assert mod._fused_step is not None and not mod._fused_step.broken
import hashlib
args, _ = mod.get_params()
h = hashlib.sha256()
for k in sorted(args):
    h.update(args[k].asnumpy().tobytes())
print(json.dumps(dict(mxc.stats()["counters"], sha=h.hexdigest())))
''' % (cache, ckpt, ckpt)
    counters = []
    for _ in range(2):
        r = subprocess.run([sys.executable, "-c", script], cwd=REPO,
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        counters.append(json.loads(r.stdout.strip().splitlines()[-1]))
    first, resumed = counters
    assert first["compiles"] >= 1 and first["stores"] >= 1
    payload = _entry_files(os.path.join(ckpt, "programs"))
    assert payload, "checkpoint must carry a programs/ payload"
    for p in payload:
        assert _unframe(open(p, "rb").read()) is not None
    assert resumed["compiles"] == 0, resumed   # restart skips XLA entirely
    assert resumed["disk_hits"] >= 1
    # the payload-loaded executable must also be CORRECT: a control run
    # resuming from the same first-run checkpoint with the program
    # cache OFF (plain jax.jit) must reach the identical params.
    # Regression for the donated host-staged-buffer corruption the
    # fused steps now defuse with reown_for_donation: before that fix
    # the payload-resumed sha differed nondeterministically (~30-50%).
    import shutil
    for d in os.listdir(ckpt):
        # drop the checkpoints the RESUMED run committed so the control
        # resumes from the same state the resumed run started at
        if d.startswith("ckpt-") and int(d.split("-")[1]) > 8:
            shutil.rmtree(os.path.join(ckpt, d), ignore_errors=True)
    r = subprocess.run([sys.executable, "-c", script], cwd=REPO,
                       capture_output=True, text=True, timeout=300,
                       env=dict(os.environ, MXNET_PROGRAM_CACHE="0"))
    assert r.returncode == 0, r.stderr[-2000:]
    control = json.loads(r.stdout.strip().splitlines()[-1])
    assert control["sha"] == resumed["sha"], \
        "payload-resumed params differ from plain-jit resume"


def test_cache_report_tool(tmp_path):
    """mxlint --cache-report aggregates the stats sidecar."""
    a = np.ones((4, 4), "f4")
    cache = ProgramCache(tmp_path)
    c = cached_jit(_fn, graph_key="report", label="report-prog",
                   cache=cache)
    c(a, a)
    cache.write_stats()
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "mxlint_cli", os.path.join(REPO, "tools", "mxlint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.cache_report(str(tmp_path), as_json=True) == 0


def test_disabled_knob_restores_plain_jit(tmp_path, monkeypatch):
    """MXNET_PROGRAM_CACHE=0: wrappers degrade to plain jax.jit — no
    disk traffic, results unchanged."""
    monkeypatch.setattr(mxc, "_enabled", False)
    a = np.ones((3, 3), "f4")
    cache = ProgramCache(tmp_path)
    c = cached_jit(_fn, graph_key="off", cache=cache)
    out = np.asarray(c(a, a))
    np.testing.assert_allclose(out, np.tanh(a @ a) + 1.0, rtol=1e-6)
    assert not _entry_files(tmp_path)
    assert cache.counters["compiles"] == 0  # accounting off with the layer
    assert cache.counters["stores"] == 0


def test_corrupt_source_payload_is_repaired_on_export(tmp_path):
    """A torn entry in a read-only source (checkpoint programs/ payload)
    cannot be deleted there — but the next export of that key must
    REWRITE it instead of skipping the existing bad file, or every
    future consumer pays the compile forever."""
    a = np.ones((5, 5), "f4")
    payload = tmp_path / "programs"
    c1 = cached_jit(_fn, graph_key="repair", cache=ProgramCache())
    c1(a, a)
    assert c1.export_to(payload) == 1
    (path,) = _entry_files(payload)
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:len(blob) // 2])       # torn copy

    consumer = ProgramCache(sources=[str(payload)])
    c2 = cached_jit(_fn, graph_key="repair", cache=consumer)
    c2(a, a)
    assert consumer.counters["corrupt"] == 1
    assert c2.compile_count == 1             # fell back to compile
    assert c2.export_to(payload) == 1        # rewrites the bad entry

    fresh = ProgramCache(sources=[str(payload)])
    c3 = cached_jit(_fn, graph_key="repair", cache=fresh)
    c3(a, a)
    assert c3.compile_count == 0 and c3.disk_hits == 1   # repaired
