"""Legacy symbolic RNN API tests (reference
tests/python/unittest/test_rnn.py): cell unrolling, fused equivalence,
BucketSentenceIter semantics."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import rnn


def test_lstm_cell_unroll_shapes():
    cell = rnn.LSTMCell(16, prefix="l_")
    inputs = [mx.sym.Variable(f"t{i}") for i in range(3)]
    outputs, states = cell.unroll(3, inputs)
    out = mx.sym.Group(outputs)
    args = {f"t{i}": (2, 8) for i in range(3)}
    _, out_shapes, _ = out.infer_shape(**args)
    assert out_shapes == [(2, 16)] * 3
    assert len(states) == 2


def test_stacked_cells_train_reduces_loss():
    V, E, H, T, B = 30, 8, 16, 6, 8
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(H, prefix="lstm_l0_"))
    stack.add(rnn.GRUCell(H, prefix="gru_l1_"))

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=V, output_dim=E)
    outputs, _ = stack.unroll(T, inputs=embed, merge_outputs=True)
    pred = mx.sym.Reshape(outputs, shape=(-1, H))
    pred = mx.sym.FullyConnected(pred, num_hidden=V)
    net = mx.sym.SoftmaxOutput(pred, mx.sym.Reshape(label, shape=(-1,)))

    rng = np.random.RandomState(0)
    X = rng.randint(0, V, (64, T)).astype("f4")
    Y = np.roll(X, -1, axis=1)
    it = mx.io.NDArrayIter(X, Y, batch_size=B)
    mod = mx.mod.Module(net, context=mx.cpu())
    metric = mx.metric.Perplexity(None)
    mod.fit(it, num_epoch=12, optimizer="adam", eval_metric=metric,
            optimizer_params={"learning_rate": 0.01,
                              "rescale_grad": 1.0 / (B * T)})
    it.reset()
    final = dict(mod.score(it, mx.metric.Perplexity(None)))["perplexity"]
    assert final < V * 0.8, final     # better than uniform guessing


def test_fused_cell_runs_and_unfuses():
    cell = rnn.FusedRNNCell(12, num_layers=2, mode="lstm", prefix="f_")
    data = mx.sym.Variable("data")
    outputs, states = cell.unroll(5, inputs=data, layout="NTC",
                                  merge_outputs=True)
    _, out_shapes, _ = outputs.infer_shape(data=(4, 5, 7))
    assert out_shapes[0] == (4, 5, 12)
    stack = cell.unfuse()
    assert len(stack._cells) == 2


def test_bucket_sentence_iter():
    rng = np.random.RandomState(0)
    sentences = [list(rng.randint(1, 50, rng.randint(3, 20)))
                 for _ in range(200)]
    it = rnn.BucketSentenceIter(sentences, batch_size=8,
                                buckets=[10, 20], invalid_label=0)
    assert it.default_bucket_key == 20
    n = 0
    for batch in it:
        assert batch.bucket_key in (10, 20)
        data = batch.data[0].asnumpy()
        label = batch.label[0].asnumpy()
        assert data.shape == (8, batch.bucket_key)
        # label is data shifted left by one
        np.testing.assert_array_equal(label[:, :-1], data[:, 1:])
        n += 1
    assert n > 0


def test_encode_sentences():
    coded, vocab = rnn.encode_sentences([["a", "b"], ["b", "c"]],
                                        start_label=1)
    assert coded[0][1] == coded[1][0]      # shared token -> same id
    assert len(vocab) == 4                 # 3 tokens + invalid key
