"""Serving runtime: dynamic micro-batching over a shape-bucketed program
cache (the ISSUE-4 acceptance gates).

Covers: single-request parity with Module.forward through the shared
program cache, concurrent clients with per-request order preserved,
deadline-exceeded errors naming the model and timeout, backpressure on a
bounded queue, graceful drain on shutdown/unload, the zero-post-warmup-
recompile certification via `analysis.recompile` across mixed request
sizes, the >=2x dynamic-batching throughput gate at concurrency 8, the
C-predict reroute, `io.pad_to_bucket` + ragged-tail `Module.predict`
reusing one compiled program, checkpoint-dir model loading, and monitor
installation on the request path.
"""
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import analysis, io, sym
from incubator_mxnet_tpu.base import MXNetError


def _mlp(in_dim, hidden, n_out=3, prefix=""):
    net = sym.Variable("data")
    for i, h in enumerate(hidden):
        net = sym.FullyConnected(net, num_hidden=h, name=f"{prefix}fc{i}")
        net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=n_out, name=f"{prefix}head")
    return sym.SoftmaxOutput(net, name="softmax")


def _make_model(in_dim=6, hidden=(16,), n_out=3, batch=4, seed=0):
    """(symbol, arg_params, aux_params, reference Module) ready to serve."""
    np.random.seed(seed)
    mx.random.seed(seed)
    net = _mlp(in_dim, hidden, n_out)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (batch, in_dim))],
             label_shapes=[io.DataDesc("softmax_label", (batch,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    args, auxs = mod.get_params()
    return net, args, auxs, mod


def _expect(mod, x, batch):
    """Reference outputs for `x` (n rows) via Module.forward row blocks."""
    outs = []
    for lo in range(0, x.shape[0], batch):
        rows = x[lo:lo + batch]
        pad = batch - rows.shape[0]
        if pad:
            rows = np.concatenate([rows, np.repeat(rows[-1:], pad, 0)])
        mod.forward(io.DataBatch(data=[mx.nd.array(rows)],
                                 label=[mx.nd.zeros((batch,))]),
                    is_train=False)
        outs.append(mod.get_outputs()[0].asnumpy()[:batch - pad])
    return np.concatenate(outs)


def test_single_request_parity_and_program_cache():
    net, args, auxs, mod = _make_model()
    m = mx.serving.ServedModel(net, args, auxs,
                               data_shapes=[("data", (1, 6))],
                               buckets=(1, 2, 4), ctx=mx.cpu(), name="par")
    m.warmup()
    assert m.program_count() == 3
    x = np.random.randn(4, 6).astype(np.float32)
    expect = _expect(mod, x, 4)
    got = m.infer({"data": x})[0].asnumpy()
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)
    # a ragged request pads up to bucket 4 and slices back to 3 rows
    got3 = m.infer({"data": x[:3]})[0].asnumpy()
    assert got3.shape[0] == 3
    np.testing.assert_allclose(got3, expect[:3], rtol=1e-5, atol=1e-6)
    # both calls reused warmup's programs
    assert m.program_count() == 3


def test_concurrent_clients_correct_and_ordered():
    net, args, auxs, mod = _make_model()
    x = np.random.randn(64, 6).astype(np.float32)
    expect = _expect(mod, x, 4)
    with mx.serving.ModelServer(max_queue_latency_ms=2.0) as srv:
        srv.load_model("toy", symbol=net, arg_params=args, aux_params=auxs,
                       data_shapes=[("data", (1, 6))], buckets=(1, 2, 4, 8))
        n_clients, per = 8, 8
        results = [None] * n_clients
        errors = []

        def client(c):
            try:
                futs = [srv.submit("toy", {"data": x[(c * per + i) % 64][None]})
                        for i in range(per)]
                results[c] = [f.result(30)[0].asnumpy() for f in futs]
            except Exception as exc:
                errors.append(exc)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
        # every client's responses line up with ITS submission order
        for c in range(n_clients):
            for i, got in enumerate(results[c]):
                np.testing.assert_allclose(
                    got[0], expect[(c * per + i) % 64], rtol=1e-5, atol=1e-6)
        snap = srv.stats()["toy"]
        assert snap["responses"] == n_clients * per
        assert 0.0 < snap["batch_occupancy"] <= 1.0


def test_deadline_exceeded_names_model_and_timeout():
    net, args, auxs, _ = _make_model()
    with mx.serving.ModelServer() as srv:
        srv.load_model("slowpoke", symbol=net, arg_params=args,
                       aux_params=auxs, data_shapes=[("data", (1, 6))],
                       buckets=(1,))
        batcher = srv.batcher("slowpoke")
        batcher.pause()
        try:
            fut = srv.submit("slowpoke",
                             {"data": np.zeros((1, 6), np.float32)},
                             timeout_ms=5)
            time.sleep(0.05)
        finally:
            batcher.resume()
        with pytest.raises(MXNetError, match=r"slowpoke.*5.*ms"):
            fut.result(30)
        assert srv.stats()["slowpoke"]["timeouts"] == 1


def test_backpressure_bounded_queue():
    net, args, auxs, _ = _make_model()
    with mx.serving.ModelServer(max_queue=4) as srv:
        srv.load_model("bp", symbol=net, arg_params=args, aux_params=auxs,
                       data_shapes=[("data", (1, 6))], buckets=(1, 2, 4, 8))
        batcher = srv.batcher("bp")
        batcher.pause()
        x = np.zeros((1, 6), np.float32)
        accepted = []
        try:
            with pytest.raises(MXNetError, match="backpressure"):
                # queue(4) + at most one request held by the worker
                for _ in range(6):
                    accepted.append(srv.submit("bp", {"data": x}))
        finally:
            batcher.resume()
        assert 4 <= len(accepted) <= 5
        assert srv.stats()["bp"]["rejected"] == 1
        for f in accepted:   # rejected request lost, accepted ones serve
            assert len(f.result(30)) == 1


def test_drain_on_shutdown_completes_in_flight():
    net, args, auxs, mod = _make_model()
    x = np.random.randn(16, 6).astype(np.float32)
    expect = _expect(mod, x, 4)
    srv = mx.serving.ModelServer(max_queue_latency_ms=1.0)
    srv.load_model("d", symbol=net, arg_params=args, aux_params=auxs,
                   data_shapes=[("data", (1, 6))], buckets=(1, 2, 4))
    futs = [srv.submit("d", {"data": x[i][None]}) for i in range(16)]
    srv.shutdown(drain=True)
    for i, f in enumerate(futs):
        assert f.done()
        np.testing.assert_allclose(f.result()[0].asnumpy()[0], expect[i],
                                   rtol=1e-5, atol=1e-6)
    with pytest.raises(MXNetError, match="no model"):
        srv.submit("d", {"data": x[0][None]})


def test_unload_drains_without_dropping():
    net, args, auxs, _ = _make_model()
    with mx.serving.ModelServer() as srv:
        srv.load_model("u", symbol=net, arg_params=args, aux_params=auxs,
                       data_shapes=[("data", (1, 6))], buckets=(1, 2, 4))
        x = np.zeros((1, 6), np.float32)
        futs = [srv.submit("u", {"data": x}) for _ in range(8)]
        srv.unload_model("u", drain=True)
        assert all(f.done() and len(f.result()) == 1 for f in futs)
        assert "u" not in srv.models()


def test_zero_recompiles_after_warmup_mixed_sizes():
    net, args, auxs, _ = _make_model()
    buckets = (1, 2, 4, 8)
    with mx.serving.ModelServer(max_queue_latency_ms=1.0) as srv:
        model = srv.load_model("audit", symbol=net, arg_params=args,
                               aux_params=auxs,
                               data_shapes=[("data", (1, 6))],
                               buckets=buckets)
        key = model.audit_key
        sigs_after_warmup = analysis.recompile.signatures(key)
        assert len(sigs_after_warmup) == len(buckets)

        def client(rows):
            x = np.zeros((rows, 6), np.float32)
            for _ in range(6):
                srv.predict("audit", {"data": x}, timeout_ms=10000)

        threads = [threading.Thread(target=client, args=(r,))
                   for r in (1, 2, 3, 5, 7, 8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # mixed request sizes all landed in warmed buckets: no new
        # signatures, no shape-churn findings, no fresh XLA programs
        assert analysis.recompile.signatures(key) == sigs_after_warmup
        assert not [f for f in analysis.recompile.findings()
                    if key in (f.location or "")]
        assert model.program_count() == len(buckets)


def test_dynamic_batching_2x_throughput_concurrency8():
    """The acceptance gate: >=2x over a sequential single-request loop at
    concurrency 8 (compute-bound model, so batching has something to
    amortize; measured margin on the CPU suite is >5x)."""
    net, args, auxs, _ = _make_model(in_dim=1024, hidden=(2048, 2048),
                                     batch=1)
    m = mx.serving.ServedModel(net, args, auxs,
                               data_shapes=[("data", (1, 1024))],
                               buckets=(1, 2, 4, 8), ctx=mx.cpu(),
                               name="tp")
    m.warmup()
    x = np.random.randn(1, 1024).astype(np.float32)
    n_clients, per = 8, 12

    t0 = time.monotonic()
    for _ in range(n_clients * per):
        m.infer({"data": x})
    sequential_s = time.monotonic() - t0

    with mx.serving.ModelServer(max_queue_latency_ms=4.0) as srv:
        srv.load_model("tp", model=m, warmup=False)

        def client():
            for _ in range(per):
                srv.predict("tp", {"data": x}, timeout_ms=60000)

        threads = [threading.Thread(target=client)
                   for _ in range(n_clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        batched_s = time.monotonic() - t0
        snap = srv.stats()["tp"]
    assert snap["responses"] == n_clients * per
    speedup = sequential_s / batched_s
    assert speedup >= 2.0, (
        f"dynamic batching speedup {speedup:.2f}x < 2x "
        f"(sequential {sequential_s:.3f}s, batched {batched_s:.3f}s, "
        f"avg batch rows {snap['avg_batch_rows']:.1f})")
    assert snap["avg_batch_rows"] > 1.5   # coalescing actually happened


def test_c_predict_routes_through_serving(tmp_path):
    """The C-predict parity API and the serving runtime share one
    program cache; outputs match Module.forward exactly."""
    net, args, auxs, mod = _make_model()
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 0)
    with open(prefix + "-symbol.json") as f:
        symbol_json = f.read()
    with open(prefix + "-0000.params", "rb") as f:
        param_bytes = f.read()
    from incubator_mxnet_tpu import c_predict
    pred = c_predict.create(symbol_json, param_bytes, 1, 0, ["data"],
                            [(4, 6)])
    x = np.random.randn(4, 6).astype(np.float32)
    pred.set_input("data", x.ravel())
    pred.forward()
    assert pred.output_shape(0) == (4, 3)
    got = np.frombuffer(pred.output(0), np.float32).reshape(4, 3)
    expect = _expect(mod, x, 4)
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)
    # the predictor IS a served model: same single-request path
    assert pred._model.program_count() == 1


def test_served_model_from_checkpoint_dir(tmp_path):
    net, args, auxs, mod = _make_model()
    symbol_file = str(tmp_path / "net-symbol.json")
    net.save(symbol_file)
    root = str(tmp_path / "ckpts")
    mgr = mx.checkpoint.CheckpointManager(root, async_snapshots=False)
    arrays = {f"arg:{k}": v.asnumpy() for k, v in args.items()}
    arrays.update({f"aux:{k}": v.asnumpy() for k, v in auxs.items()})
    mgr.snapshot(arrays=arrays, step=1)
    mgr.close()
    m = mx.serving.ServedModel.from_checkpoint_dir(
        symbol_file, root, data_shapes=[("data", (1, 6))], buckets=(4,),
        ctx=mx.cpu(), name="ckpt")
    x = np.random.randn(4, 6).astype(np.float32)
    got = m.infer({"data": x})[0].asnumpy()
    np.testing.assert_allclose(got, _expect(mod, x, 4), rtol=1e-5,
                               atol=1e-6)


def test_pad_to_bucket_helper():
    x = np.arange(18, dtype=np.float32).reshape(3, 6)
    b = io.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.zeros((3,))])
    padded = b.pad_to_bucket((4, 8))
    assert padded.data[0].shape == (4, 6)
    assert padded.label[0].shape == (4,)
    assert padded.pad == 1
    # pad rows replicate the final sample
    np.testing.assert_array_equal(padded.data[0].asnumpy()[3], x[2])
    # already bucket-sized -> unchanged object; oversized -> unchanged
    b4 = io.DataBatch(data=[mx.nd.zeros((4, 6))])
    assert io.pad_to_bucket(b4, (4, 8)) is b4
    b9 = io.DataBatch(data=[mx.nd.zeros((9, 6))])
    assert io.pad_to_bucket(b9, (4, 8)) is b9


class _RaggedIter(io.DataIter):
    """Yields full batches then a ragged tail (the recompile hazard)."""

    def __init__(self, x, y, batch_size):
        super().__init__(batch_size)
        self._x, self._y = x, y
        self._cur = 0

    def reset(self):
        self._cur = 0

    def next(self):
        if self._cur >= self._x.shape[0]:
            raise StopIteration
        lo = self._cur
        hi = min(lo + self.batch_size, self._x.shape[0])
        self._cur = hi
        return io.DataBatch(data=[mx.nd.array(self._x[lo:hi])],
                            label=[mx.nd.array(self._y[lo:hi])])


def test_predict_ragged_tail_reuses_one_program():
    net, args, auxs, mod = _make_model()
    x = np.random.randn(10, 6).astype(np.float32)   # 10 % 4 != 0
    y = np.zeros(10, np.float32)
    expect = _expect(mod, x, 4)
    exe = mod._exec_group.execs[0]
    before = exe._fwd_jit[False]._cache_size()
    out = mod.predict(_RaggedIter(x, y, 4))
    assert out.shape[0] == 10
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-5, atol=1e-6)
    # the padded tail reused the full-batch program: no new signature
    assert exe._fwd_jit[False]._cache_size() == before


def test_zero_row_request_rejected():
    net, args, auxs, _ = _make_model()
    m = mx.serving.ServedModel(net, args, auxs,
                               data_shapes=[("data", (1, 6))],
                               buckets=(1, 2), ctx=mx.cpu(), name="z")
    with pytest.raises(MXNetError, match="no rows"):
        m.infer({"data": np.zeros((0, 6), np.float32)})


def test_shutdown_while_paused_does_not_deadlock():
    net, args, auxs, _ = _make_model()
    srv = mx.serving.ModelServer()
    srv.load_model("p", symbol=net, arg_params=args, aux_params=auxs,
                   data_shapes=[("data", (1, 6))], buckets=(1, 2))
    srv.batcher("p").pause()
    fut = srv.submit("p", {"data": np.zeros((1, 6), np.float32)})
    t0 = time.monotonic()
    srv.shutdown(drain=True)   # close un-pauses; in-flight work completes
    assert time.monotonic() - t0 < 10
    assert len(fut.result(1)) == 1


def test_cancelled_future_does_not_kill_worker():
    net, args, auxs, _ = _make_model()
    with mx.serving.ModelServer() as srv:
        srv.load_model("c", symbol=net, arg_params=args, aux_params=auxs,
                       data_shapes=[("data", (1, 6))], buckets=(1, 2, 4))
        batcher = srv.batcher("c")
        batcher.pause()
        x = np.zeros((1, 6), np.float32)
        doomed = srv.submit("c", {"data": x})
        queued = srv.submit("c", {"data": x})
        assert doomed.cancel() or queued.cancel()   # at least one pending
        batcher.resume()
        assert len(queued.result(30)) == 1 if not queued.cancelled() \
            else len(doomed.result(30)) == 1
        # the worker survived the cancelled future: new requests serve
        assert len(srv.predict("c", {"data": x})) == 1


def test_c_predict_inputs_without_shared_batch_axis(tmp_path):
    """The ABI contract `infer_exact` preserves: input shapes need not
    agree on a leading batch dimension (old `simple_bind` semantics)."""
    data = sym.Variable("data")
    scale = sym.Variable("scale")
    net = sym.broadcast_mul(data, scale)
    mod = mx.mod.Module(net, data_names=("data", "scale"), label_names=(),
                        context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (4, 6)),
                          io.DataDesc("scale", (1, 6))],
             for_training=False, grad_req="null")
    mod.init_params()
    prefix = str(tmp_path / "mi")
    mod.save_checkpoint(prefix, 0)
    with open(prefix + "-symbol.json") as f:
        symbol_json = f.read()
    with open(prefix + "-0000.params", "rb") as f:
        param_bytes = f.read()
    from incubator_mxnet_tpu import c_predict
    pred = c_predict.create(symbol_json, param_bytes, 1, 0,
                            ["data", "scale"], [(4, 6), (1, 6)])
    x = np.random.randn(4, 6).astype(np.float32)
    s = np.random.randn(1, 6).astype(np.float32)
    pred.set_input("data", x.ravel())
    pred.set_input("scale", s.ravel())
    pred.forward()
    got = np.frombuffer(pred.output(0), np.float32).reshape(4, 6)
    np.testing.assert_allclose(got, x * s, rtol=1e-5, atol=1e-6)


def test_monitor_installs_on_request_path():
    net, args, auxs, _ = _make_model()
    seen = []

    def stat(arr):   # over BATCHED outputs; returns a plain float
        seen.append(tuple(arr.shape))
        return float(arr.abs().sum().asnumpy())

    mon = mx.monitor.Monitor(interval=1, stat_func=stat, pattern="softmax")
    with mx.serving.ModelServer(max_queue_latency_ms=1.0) as srv:
        srv.load_model("mon", symbol=net, arg_params=args, aux_params=auxs,
                       data_shapes=[("data", (1, 6))], buckets=(1, 2, 4))
        srv.install_monitor("mon", mon)
        x = np.random.randn(4, 6).astype(np.float32)
        srv.predict("mon", {"data": x})
    # the batcher drove tic/toc_print around the executed batch (no
    # crash on a serving executor without arg arrays), and the stat
    # function saw the batched bucket-4 outputs
    assert seen and seen[0][0] == 4
