#!/usr/bin/env python
"""mxlint — static TPU-hazard linter for symbol graphs and scripts.

Front ends (analysis/ package):

* saved symbol JSON  — duplicate/empty names, unreachable nodes, dead
  outputs, aux races, f64 promotion, unbound inputs, TPU tile hints;
* python scripts     — AST lints: `.asnumpy()`/`.asscalar()`/
  `.wait_to_read()`/`waitall()` inside loops (host-sync-in-loop),
  literal ``kvstore='local'`` in TPU scripts.

Usage:
    python tools/mxlint.py PATH [PATH ...]
        PATH: a .py script, a symbol .json, or a directory (scanned
        recursively for both).
    --hints            include perf hints (tpu-layout) in the output
    --shape name=d,... seed graph shape inference (repeatable), e.g.
                       --shape data=64,3,224,224
    --suppress codes   comma list of finding codes to drop
    --json             machine-readable summary (one JSON object)

Exit status: 0 when no error/warn findings survive, 1 otherwise (hints
never fail the run).  Inline suppression: ``# mxlint: disable[=code]``
on the offending source line, or a ``__lint__`` attr on a graph node.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _collect(paths):
    py, js = [], []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    full = os.path.join(root, f)
                    if f.endswith(".py"):
                        py.append(full)
                    elif f.endswith(".json"):
                        js.append(full)
        elif p.endswith(".py"):
            py.append(p)
        elif p.endswith(".json"):
            js.append(p)
        else:
            print(f"mxlint: skipping {p!r} (not a .py/.json or directory)",
                  file=sys.stderr)
    return py, js


def _looks_like_symbol_json(text):
    head = text.lstrip()[:1]
    return head == "{" and '"nodes"' in text


def _parse_shapes(items):
    shapes = {}
    for item in items or ():
        name, _, dims = item.partition("=")
        if not dims:
            raise SystemExit(f"mxlint: bad --shape {item!r} "
                             "(want name=d0,d1,...)")
        shapes[name] = tuple(int(d) for d in dims.split(",") if d)
    return shapes


def main(argv=None):
    ap = argparse.ArgumentParser(prog="mxlint", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="+")
    ap.add_argument("--hints", action="store_true",
                    help="include perf hints (tpu-layout)")
    ap.add_argument("--shape", action="append", default=[],
                    metavar="NAME=D0,D1,...")
    ap.add_argument("--suppress", default="",
                    metavar="CODE[,CODE...]")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    from incubator_mxnet_tpu import analysis
    shapes = _parse_shapes(args.shape)
    suppress = {c.strip() for c in args.suppress.split(",") if c.strip()}

    py_files, json_files = _collect(args.paths)
    reports = []
    scanned = 0
    for path in py_files:
        scanned += 1
        reports.append(analysis.check_source_file(path))
    for path in json_files:
        with open(path, encoding="utf-8", errors="replace") as f:
            text = f.read()
        if not _looks_like_symbol_json(text):
            continue  # round artifacts etc., not graphs
        scanned += 1
        reports.append(analysis.check_json(text, shapes=shapes or None,
                                           hints=args.hints, target=path))

    findings = []
    for r in reports:
        r = r.suppress(suppress)
        if not args.hints:
            r = r.filter(max_severity=analysis.WARN)
        findings.extend(r.findings)

    by_code, by_pass = {}, {}
    for f in findings:
        by_code[f.code] = by_code.get(f.code, 0) + 1
        by_pass[f.pass_name] = by_pass.get(f.pass_name, 0) + 1
    failing = [f for f in findings if f.severity in ("error", "warn")]

    if args.as_json:
        print(json.dumps({
            "scanned": scanned,
            "findings": len(findings),
            "failing": len(failing),
            "by_code": by_code,
            "by_pass": by_pass,
            "items": [f.as_dict() for f in findings[:200]],
        }, indent=1))
    else:
        for f in findings:
            print(f.format())
        print(f"mxlint: {scanned} file(s) scanned, "
              f"{len(findings)} finding(s)"
              + (f" ({json.dumps(by_code)})" if findings else ""))
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
