#!/usr/bin/env python
"""mxlint — static TPU-hazard linter for symbol graphs and scripts.

Front ends (analysis/ package):

* saved symbol JSON  — duplicate/empty names, unreachable nodes, dead
  outputs, aux races, f64 promotion, unbound inputs, TPU tile hints;
* python scripts     — AST lints: `.asnumpy()`/`.asscalar()`/
  `.wait_to_read()`/`waitall()` inside loops (host-sync-in-loop),
  literal ``kvstore='local'`` in TPU scripts, unbounded retry loops,
  swallowing excepts, unsupervised collectives, and direct
  `ServedModel.infer`/`ModelServer` use in router-configured scripts
  (router-bypass).

Usage:
    python tools/mxlint.py PATH [PATH ...]
        PATH: a .py script, a symbol .json, or a directory (scanned
        recursively for both).
    --hints            include perf hints (tpu-layout) in the output
    --shape name=d,... seed graph shape inference (repeatable), e.g.
                       --shape data=64,3,224,224
    --suppress codes   comma list of finding codes to drop
    --fail-on SEV      severity threshold for the exit status: exit 1
                       when any finding at/above SEV (one of error,
                       warn, hint) survives --suppress.  Default: warn
                       (hints never fail).  --fail-on=hint implies
                       --hints.
    --json             machine-readable summary (one JSON object)
    --tsan-report      concurrency report: the mxtsan AST lints
                       (unnamed-thread, bare-acquire, sleep-under-lock,
                       unjoined-thread-in-init) over PATHS (default:
                       the package), plus any MXNET_TSAN_LOG runtime
                       dump among PATHS rendered as the lock-order
                       graph + findings
    --cache-report DIR program-cache hit rates / churn from stats.json
    --cost-report      mxcost static cost analysis (analysis/cost.py):
                       the canonical bench program set (per-program
                       flops/bytes/roofline, dtype-flow defects, peak
                       HBM) plus the dp-N bucketed collective plan, and
                       any symbol-JSON PATHS as extra programs.
                       --budgets FILE compares against the committed
                       COST_BUDGETS baseline (in-budget defects demote
                       to hints; regressions are errors);
                       --write-budgets FILE re-snapshots the baseline;
                       --profile/--dp/--bucket-mb pick the device
                       profile and plan geometry.
    --shard-report     mxshard static SPMD sharding analysis
                       (analysis/sharding.py): PartitionSpec
                       propagation over the bench program set (and any
                       symbol-JSON PATHS) under --mesh — hidden
                       reshards, implicit replication, rule-coverage
                       gaps, dp-axis leaks, per-device peak HBM, and
                       the per-step ICI byte bill.  --budgets FILE
                       gates against the COST_BUDGETS "sharding"
                       section; --write-budgets FILE re-snapshots it;
                       --measured pushes the bench convnet's sharded
                       gradients through a real KVStore and fails on
                       >10% static-vs-measured disagreement.

Exit status (the CI contract): 0 — no finding at/above --fail-on
survived --suppress; 1 — at least one did; 2 — usage error (argparse).
Inline suppression: ``# mxlint: disable[=code]`` on the offending
source line, or a ``__lint__`` attr on a graph node.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _collect(paths):
    py, js = [], []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    full = os.path.join(root, f)
                    if f.endswith(".py"):
                        py.append(full)
                    elif f.endswith(".json"):
                        js.append(full)
        elif p.endswith(".py"):
            py.append(p)
        elif p.endswith(".json"):
            js.append(p)
        else:
            print(f"mxlint: skipping {p!r} (not a .py/.json or directory)",
                  file=sys.stderr)
    return py, js


def _looks_like_symbol_json(text):
    head = text.lstrip()[:1]
    return head == "{" and '"nodes"' in text


def _parse_shapes(items):
    shapes = {}
    for item in items or ():
        name, _, dims = item.partition("=")
        if not dims:
            raise SystemExit(f"mxlint: bad --shape {item!r} "
                             "(want name=d0,d1,...)")
        shapes[name] = tuple(int(d) for d in dims.split(",") if d)
    return shapes


def cache_report(cache_dir, as_json=False):
    """Program-cache report over a cache directory's ``stats.json``
    (written by the compile/ subsystem at process exit and by the
    warmup CLI): aggregate hit rates across recorded runs, per-program
    compile counts, and compiles attributed to churned signatures — a
    program compiled under more than one distinct signature paid a full
    XLA compile for each one, which is the shape-churn cost the
    recompile auditor diagnoses at runtime."""
    stats_path = os.path.join(cache_dir, "stats.json")
    try:
        with open(stats_path) as f:
            runs = json.load(f).get("runs", [])
    except (OSError, ValueError) as e:
        print(f"mxlint: no readable stats at {stats_path} ({e})",
              file=sys.stderr)
        return 1
    total = {"compiles": 0, "disk_hits": 0, "mem_hits": 0, "stores": 0,
             "corrupt": 0, "evicted": 0}
    by_label = {}
    sigs_by_label = {}
    for run in runs:
        for k in total:
            total[k] += run.get("counters", {}).get(k, 0)
        for ev in run.get("events", []):
            lab = ev.get("label", "?")
            by_label[lab] = by_label.get(lab, 0) + 1
            sigs_by_label.setdefault(lab, set()).add(ev.get("signature"))
    lookups = total["compiles"] + total["disk_hits"] + total["mem_hits"]
    churned = {lab: {"compiles": n,
                     "distinct_signatures": len(sigs_by_label[lab])}
               for lab, n in by_label.items()
               if len(sigs_by_label.get(lab, ())) > 1}
    report = {
        "runs": len(runs),
        **total,
        "hit_rate": round((total["disk_hits"] + total["mem_hits"]) /
                          lookups, 4) if lookups else None,
        "compiles_by_program": dict(sorted(by_label.items(),
                                           key=lambda kv: -kv[1])[:50]),
        "churned_signature_programs": churned,
    }
    if as_json:
        print(json.dumps(report, indent=1))
    else:
        print("program cache report (%d run(s)): %d compiles, %d disk "
              "hits, %d memory hits, hit rate %s"
              % (report["runs"], total["compiles"], total["disk_hits"],
                 total["mem_hits"],
                 "n/a" if report["hit_rate"] is None
                 else "%.1f%%" % (100 * report["hit_rate"])))
        if total["corrupt"] or total["evicted"]:
            print("  %d corrupt entries dropped, %d evicted"
                  % (total["corrupt"], total["evicted"]))
        for lab, n in sorted(by_label.items(), key=lambda kv: -kv[1]):
            mark = ""
            if lab in churned:
                mark = "  <- %d distinct signatures, one full XLA " \
                    "compile each (declared buckets or shape churn; " \
                    "MXNET_ANALYSIS=1 runtime report separates them)" \
                    % churned[lab]["distinct_signatures"]
            print("  %4d compile(s)  %s%s" % (n, lab, mark))
    return 0


def cost_report(paths, as_json=False, budgets_path=None,
                write_budgets=None, profile=None, dp=8, bucket_mb=None,
                suppress=(), fail_on="warn", shapes=None):
    """mxcost stage: analyze the canonical bench program set (plus any
    symbol-JSON PATHS) with analysis/cost.py, optionally gate against a
    COST_BUDGETS baseline, and exit per --fail-on.  This is the CI
    entry `run_tpu_parity.py`'s cost stage runs: a new dequant chain,
    f32 upcast, extra collective, +bytes/step or +peak-HBM beyond the
    committed budget exits 1."""
    from incubator_mxnet_tpu.analysis import Report
    from incubator_mxnet_tpu.analysis import cost as mxcost
    from incubator_mxnet_tpu.analysis import budgets as mxbudgets
    from incubator_mxnet_tpu.analysis.findings import severity_rank
    from incubator_mxnet_tpu.symbol.symbol import load_json

    cap = int(bucket_mb * (1 << 20)) if bucket_mb else None
    results = mxcost.analyze_bench_set(profile=profile, dp=dp,
                                       cap_bytes=cap)
    _py, json_files = _collect(paths)
    for path in json_files:
        with open(path, encoding="utf-8", errors="replace") as f:
            text = f.read()
        if not _looks_like_symbol_json(text):
            continue
        name = os.path.basename(path)
        if name in results:       # same basename twice: keep both
            name = path
        try:
            sym = load_json(text)
        except Exception as e:
            print(f"mxlint: cannot load {path} ({str(e)[:120]})",
                  file=sys.stderr)
            continue
        results[name] = mxcost.analyze_symbol(
            sym, shapes=shapes or None, profile=profile, target=name)

    if write_budgets:
        mxbudgets.save(write_budgets, mxbudgets.snapshot(results))
        print(f"mxlint: cost budgets for {len(results) - 1} program(s) "
              f"written to {write_budgets}")
        return 0

    coll_report = mxcost.collectives_report(results["__collectives__"])
    deltas = {}
    if budgets_path:
        report, deltas = mxbudgets.check(results,
                                         mxbudgets.load(budgets_path))
    else:
        report = Report(target="cost")
        for name, prog in sorted(results.items()):
            if name != "__collectives__":
                report.extend(prog.report)
    report.extend(coll_report.findings)
    report = report.suppress(set(suppress))
    thr = severity_rank(fail_on)
    failing = [f for f in report
               if severity_rank(f.severity) <= thr]

    stats = {k: v for k, v in results["__collectives__"].items()
             if k != "plan"}
    summary = {
        "programs": {name: prog.as_dict()
                     for name, prog in sorted(results.items())
                     if name != "__collectives__"},
        "collectives": stats,
        "budgets": budgets_path,
        "budget_deltas": deltas,
        "findings": len(report),
        "failing": len(failing),
        "fail_on": fail_on,
    }
    if as_json:
        print(json.dumps(summary, indent=1))
    else:
        for name, prog in sorted(results.items()):
            if name == "__collectives__":
                continue
            d = prog.as_dict()
            print("%-34s %10.3f MFLOP %9.2f MB moved  AI %6.1f  "
                  "%s-bound (%s)"
                  % (name, d["flops"] / 1e6,
                     d["bytes_moved"] / (1 << 20),
                     d["arithmetic_intensity"], d["bound"],
                     d["dominant_dtype"]))
        for f in report:
            print(f.format())
        print("mxlint --cost-report: %d program(s), %d finding(s), "
              "%d failing at --fail-on=%s%s"
              % (len(results) - 1, len(report), len(failing), fail_on,
                 " (vs %s)" % budgets_path if budgets_path else ""))
    return 1 if failing else 0


def shard_report(paths, as_json=False, budgets_path=None,
                 write_budgets=None, mesh="dp=2,tp=2", measured=False,
                 bucket_mb=None, suppress=(), fail_on="warn",
                 shapes=None):
    """mxshard stage: propagate PartitionSpecs through the committed
    bench program set (plus any symbol-JSON PATHS) under --mesh with
    analysis/sharding.py, optionally gate per-device peak HBM and
    per-step ICI bytes against the COST_BUDGETS "sharding" section,
    and (with --measured) cross-check the static dp plan against a
    real KVStore push.  This is what `run_tpu_parity.py`'s sharding
    stage runs: a new hidden reshard, a silently-replicated matrix
    param, a rule-coverage gap, or +ICI/+HBM beyond budget exits 1."""
    from incubator_mxnet_tpu.analysis import Report
    from incubator_mxnet_tpu.analysis import sharding as mxshard
    from incubator_mxnet_tpu.analysis import budgets as mxbudgets
    from incubator_mxnet_tpu.analysis.findings import Finding, severity_rank
    from incubator_mxnet_tpu.parallel.tensor_parallel import ShardingRules
    from incubator_mxnet_tpu.symbol.symbol import load_json

    cap = int(bucket_mb * (1 << 20)) if bucket_mb else None
    results = mxshard.analyze_shard_bench_set(mesh=mesh, cap_bytes=cap)

    axes = mxshard._mesh_axes(mesh)
    rules = (ShardingRules.megatron(tp_axis="tp")
             if mxshard._axis_size("tp", axes) > 1 else None)
    _py, json_files = _collect(paths)
    for path in json_files:
        with open(path, encoding="utf-8", errors="replace") as f:
            text = f.read()
        if not _looks_like_symbol_json(text):
            continue
        name = os.path.basename(path)
        if name in results:
            name = path
        try:
            sym = load_json(text)
        except Exception as e:
            print(f"mxlint: cannot load {path} ({str(e)[:120]})",
                  file=sys.stderr)
            continue
        stats = mxshard.shard_collectives(
            sym, shapes=shapes or None, mesh=mesh, rules=rules,
            cap_bytes=cap, name=name)
        rep = stats.pop("report")
        entry = rep.as_dict()
        entry["collectives"] = stats
        entry["ici_bytes_per_step"] = stats["ici_bytes_per_step"]
        results[name] = entry

    if write_budgets:
        try:
            budgets = mxbudgets.load(write_budgets)
        except (OSError, ValueError):
            budgets = {"version": 1, "programs": {}}
        budgets["sharding"] = mxshard.snapshot_shard_budgets(results,
                                                            mesh=mesh)
        mxbudgets.save(write_budgets, budgets)
        print(f"mxlint: sharding budgets for {len(results)} program(s) "
              f"written to {write_budgets}")
        return 0

    report = Report(target="sharding")
    for name, entry in sorted(results.items()):
        for d in entry.get("findings", ()):
            f = Finding(d["pass"], d["code"], d["severity"],
                        d["message"], node=d.get("node"),
                        location=d.get("location"))
            f.count = d.get("count", 1)
            report.add(f)
    deltas = {}
    if budgets_path:
        brep, deltas = mxshard.check_shard_budgets(
            results, mxbudgets.load(budgets_path))
        report.extend(brep.findings)
    report = report.suppress(set(suppress))
    thr = severity_rank(fail_on)
    failing = [f for f in report
               if severity_rank(f.severity) <= thr]

    meas = None
    if measured:
        meas = mxshard.measured_ici_check(mesh=mesh, cap_bytes=cap)

    summary = {
        "mesh": mesh if isinstance(mesh, str) else dict(axes),
        "programs": results,
        "budgets": budgets_path,
        "budget_deltas": deltas,
        "measured": meas,
        "findings": len(report),
        "failing": len(failing),
        "fail_on": fail_on,
    }
    if as_json:
        print(json.dumps(summary, indent=1))
    else:
        for name, entry in sorted(results.items()):
            print("%-24s %8.2f MB/device (replicated %8.2f MB)  "
                  "%2d tp collective(s)  %9d ICI B/step  %d reshard(s)"
                  % (name,
                     (entry.get("per_device_peak_hbm_bytes") or 0)
                     / (1 << 20),
                     (entry.get("replicated_peak_hbm_bytes") or 0)
                     / (1 << 20),
                     entry.get("tp_collectives_per_step") or 0,
                     entry.get("ici_bytes_per_step") or 0,
                     entry.get("reshard_edges") or 0))
        for f in report:
            print(f.format())
        if meas is not None:
            print("measured dp cross-check (dp=%d): static %d B/step vs "
                  "measured %d B/step, agreement %.3f%%, %s"
                  % (meas["dp"], meas["static_bytes_per_step"],
                     meas["measured_bytes_per_step"],
                     meas["agreement_pct"],
                     "OK" if meas["ok"] else "MISMATCH"))
        print("mxlint --shard-report: %d program(s) under mesh '%s', "
              "%d finding(s), %d failing at --fail-on=%s%s"
              % (len(results), mesh, len(report), len(failing), fail_on,
                 " (vs %s)" % budgets_path if budgets_path else ""))
    if meas is not None and not meas["ok"]:
        return 1
    return 1 if failing else 0


def tsan_report(paths, as_json=False):
    """Concurrency report: the mxtsan AST lint subset (unnamed-thread,
    bare-acquire, sleep-under-lock, unjoined-thread-in-init) over the
    given ``.py`` paths (default: the package), plus a render of any
    ``MXNET_TSAN_LOG`` JSON dumps passed in — the runtime sanitizer's
    findings and its lock-acquisition-order graph.  Exit 1 when any
    lint or runtime finding survives: the run_tpu_parity ``tsan`` stage
    gates on exactly this."""
    from incubator_mxnet_tpu import analysis
    from incubator_mxnet_tpu.analysis.source_lint import CONCURRENCY_CODES

    if not paths:
        paths = [os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "incubator_mxnet_tpu")]
    py_files, json_files = _collect(paths)
    lint_findings = []
    scanned = 0
    for path in py_files:
        scanned += 1
        rep = analysis.check_source_file(path)
        lint_findings.extend(f for f in rep
                             if f.code in CONCURRENCY_CODES)

    runtime = {"findings": [], "lock_graph": None, "dumps": 0}
    payloads = []
    for path in json_files:
        try:
            with open(path, encoding="utf-8") as f:
                lines = [ln for ln in f.read().splitlines()
                         if ln.strip()]
        except OSError:
            continue
        for ln in lines:   # MXNET_TSAN_LOG: one json line per process
            try:
                p = json.loads(ln)
            except ValueError:
                break      # not a tsan dump (symbol JSON etc.)
            if isinstance(p, dict) and "lock_graph" in p:
                payloads.append(p)
    for payload in payloads:
        runtime["dumps"] += 1
        runtime["findings"].extend(payload.get("findings", []))
        graph = payload.get("lock_graph") or {}
        if runtime["lock_graph"] is None:
            runtime["lock_graph"] = graph
        else:   # merge multi-process dumps (chaos runs)
            seen = {lk["name"] for lk in runtime["lock_graph"]["locks"]}
            runtime["lock_graph"]["locks"].extend(
                lk for lk in graph.get("locks", ())
                if lk["name"] not in seen)
            have = {(e["from"], e["to"])
                    for e in runtime["lock_graph"]["edges"]}
            runtime["lock_graph"]["edges"].extend(
                e for e in graph.get("edges", ())
                if (e["from"], e["to"]) not in have)

    failing = len(lint_findings) + len(runtime["findings"])
    report = {
        "scanned": scanned,
        "lint_findings": len(lint_findings),
        "runtime_findings": len(runtime["findings"]),
        "failing": failing,
        "items": [f.as_dict() for f in lint_findings[:200]],
        "runtime": runtime if runtime["dumps"] else None,
    }
    if as_json:
        print(json.dumps(report, indent=1))
    else:
        for f in lint_findings:
            print(f.format())
        for f in runtime["findings"]:
            loc = f.get("location") or ""
            print(f"{loc}: {f.get('severity')} [{f.get('code')}] "
                  f"{f.get('message')}")
        graph = runtime["lock_graph"]
        if graph:
            print("lock-order graph: %d lock(s), %d edge(s)"
                  % (len(graph.get("locks", ())),
                     len(graph.get("edges", ()))))
            for e in graph.get("edges", ()):
                print("  %s -> %s  [%s; held at %s, acquired at %s]"
                      % (e["from"], e["to"], e.get("thread"),
                         e.get("held_at"), e.get("acquired_at")))
        print(f"mxlint --tsan-report: {scanned} file(s) scanned, "
              f"{failing} finding(s)")
    return 1 if failing else 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="mxlint", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*")
    ap.add_argument("--hints", action="store_true",
                    help="include perf hints (tpu-layout)")
    ap.add_argument("--shape", action="append", default=[],
                    metavar="NAME=D0,D1,...")
    ap.add_argument("--suppress", default="",
                    metavar="CODE[,CODE...]")
    ap.add_argument("--fail-on", choices=["error", "warn", "hint"],
                    default="warn", dest="fail_on",
                    help="exit 1 when any finding at/above this "
                         "severity survives --suppress (default: warn; "
                         "hint implies --hints)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--cache-report", metavar="CACHE_DIR",
                    help="report program-cache hit rates and churn-"
                         "attributed compiles from CACHE_DIR/stats.json")
    ap.add_argument("--tsan-report", action="store_true",
                    help="concurrency report: the mxtsan AST lints over "
                         "PATHS (default: the package) + any MXNET_TSAN_"
                         "LOG runtime dumps among PATHS rendered as the "
                         "lock-order graph and findings")
    ap.add_argument("--cost-report", action="store_true",
                    help="mxcost static cost analysis of the bench "
                         "program set + symbol-JSON PATHS; gate with "
                         "--budgets / re-baseline with --write-budgets")
    ap.add_argument("--shard-report", action="store_true",
                    help="mxshard static SPMD sharding analysis of the "
                         "bench program set + symbol-JSON PATHS under "
                         "--mesh: spec propagation, hidden reshards, "
                         "implicit replication, rule coverage, per-"
                         "device peak HBM and per-step ICI bytes; gate "
                         "with --budgets / re-baseline with "
                         "--write-budgets; --measured cross-checks the "
                         "static dp plan against a real KVStore push")
    ap.add_argument("--mesh", default="dp=2,tp=2", metavar="SPEC",
                    help="mesh spec for --shard-report, e.g. 'dp=8' or "
                         "'dp=2,tp=2' (default dp=2,tp=2)")
    ap.add_argument("--measured", action="store_true",
                    help="with --shard-report: also push the bench "
                         "convnet's sharded gradients through a device "
                         "KVStore and fail on >10%% static-vs-measured "
                         "ICI disagreement")
    ap.add_argument("--budgets", metavar="JSON",
                    help="COST_BUDGETS baseline to gate --cost-report "
                         "against (regressions become errors)")
    ap.add_argument("--write-budgets", metavar="JSON",
                    dest="write_budgets",
                    help="snapshot the --cost-report analysis as a new "
                         "budget baseline and exit")
    ap.add_argument("--profile", metavar="NAME",
                    help="mxcost device profile (tpu-v3/tpu-v4/"
                         "cpu-host; default MXNET_COST_PROFILE)")
    ap.add_argument("--dp", type=int, default=8,
                    help="data-parallel degree for the --cost-report "
                         "collective plan (default 8)")
    ap.add_argument("--bucket-mb", type=float, default=None,
                    dest="bucket_mb",
                    help="bucket cap for the --cost-report collective "
                         "plan (default MXNET_KVSTORE_BUCKET_MB)")
    args = ap.parse_args(argv)

    if args.fail_on == "hint":
        args.hints = True
    if args.cache_report:
        return cache_report(args.cache_report, as_json=args.as_json)
    if args.tsan_report:
        return tsan_report(args.paths, as_json=args.as_json)
    if args.shard_report:
        return shard_report(
            args.paths, as_json=args.as_json, budgets_path=args.budgets,
            write_budgets=args.write_budgets, mesh=args.mesh,
            measured=args.measured, bucket_mb=args.bucket_mb,
            suppress={c.strip() for c in args.suppress.split(",")
                      if c.strip()},
            fail_on=args.fail_on, shapes=_parse_shapes(args.shape))
    if args.cost_report:
        return cost_report(
            args.paths, as_json=args.as_json, budgets_path=args.budgets,
            write_budgets=args.write_budgets, profile=args.profile,
            dp=args.dp, bucket_mb=args.bucket_mb,
            suppress={c.strip() for c in args.suppress.split(",")
                      if c.strip()},
            fail_on=args.fail_on, shapes=_parse_shapes(args.shape))
    if not args.paths:
        ap.error("paths required (or --cache-report DIR)")

    from incubator_mxnet_tpu import analysis
    shapes = _parse_shapes(args.shape)
    suppress = {c.strip() for c in args.suppress.split(",") if c.strip()}

    py_files, json_files = _collect(args.paths)
    reports = []
    scanned = 0
    for path in py_files:
        scanned += 1
        reports.append(analysis.check_source_file(path))
    for path in json_files:
        with open(path, encoding="utf-8", errors="replace") as f:
            text = f.read()
        if not _looks_like_symbol_json(text):
            continue  # round artifacts etc., not graphs
        scanned += 1
        reports.append(analysis.check_json(text, shapes=shapes or None,
                                           hints=args.hints, target=path))

    findings = []
    for r in reports:
        r = r.suppress(suppress)
        if not args.hints:
            r = r.filter(max_severity=analysis.WARN)
        findings.extend(r.findings)

    by_code, by_pass = {}, {}
    for f in findings:
        by_code[f.code] = by_code.get(f.code, 0) + 1
        by_pass[f.pass_name] = by_pass.get(f.pass_name, 0) + 1
    from incubator_mxnet_tpu.analysis.findings import severity_rank
    thr = severity_rank(args.fail_on)
    failing = [f for f in findings if severity_rank(f.severity) <= thr]

    if args.as_json:
        print(json.dumps({
            "scanned": scanned,
            "findings": len(findings),
            "failing": len(failing),
            "by_code": by_code,
            "by_pass": by_pass,
            "items": [f.as_dict() for f in findings[:200]],
        }, indent=1))
    else:
        for f in findings:
            print(f.format())
        print(f"mxlint: {scanned} file(s) scanned, "
              f"{len(findings)} finding(s)"
              + (f" ({json.dumps(by_code)})" if findings else ""))
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
