#!/usr/bin/env python
"""Scaling-curve bench: the 1→N data-parallel sweep next to BENCH_r05.

Sweeps dp = 1,2,4,...,N (host-platform virtual devices on CPU — the
TPU-mesh stand-in per the build contract — real devices on TPU), runs
the synthetic fused-step workloads at every point through the PUBLIC
`Module.fit` path (image model → img/s, token model → tokens/s), and
writes ``BENCH_SCALING.json``:

* per point: throughput (best of ``POINT_REPEATS`` fresh subprocesses
  — the host is shared, so one noisy-neighbor burst must not read as a
  scaling cliff), weak-scaling efficiency vs dp=1 (per-device batch
  fixed), steady-state compile count (must be ZERO in every repeat —
  certified via the unified program cache's counters), and the collective
  kvstore's communication economy for the same parameter set
  (allreduce dispatches per step, bucket count/fill histogram, overlap
  ratio, bytes reduced — `KVStore.stats()`);
* a comm-heavy A/B: the bucketed overlapped path vs the single-bucket
  `_reduce_many` it replaced (one flatten-concat of every gradient, one
  collective strictly after all of them exist) on the widest mesh —
  the ``bucketed_speedup`` gate;
* gates: dp=N efficiency >= 0.8, bucketed speedup >= 1.15, zero
  steady-state recompiles, and allreduce dispatches per step =
  O(buckets) — never O(params).

Usage:
  python tools/run_scaling.py [--devices 1,2,4,8] [--quick] [--json]
                              [--out PATH] [--platform cpu|tpu]
  (internal: --point N / --comm N run one subprocess stage)

``run_chaos.py --pod`` runs the pod-level counterpart of this sweep
(world-size curve with a SIGKILLed host mid-sweep), and
``run_tpu_parity.py`` embeds this artifact as its ``scaling`` stage.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# synthetic fused-step workloads.  Weak scaling: the per-device batch is
# fixed and each point's subprocess is PINNED to exactly ndev host cores
# (one core per virtual device — without the pin, the dp=1 control runs
# on the whole multi-core host while each of the 8 partitions runs
# ~single-threaded, poisoning the curve).  The per-device batch is sized
# so per-step compute amortizes the per-step exchange the way real
# per-chip compute amortizes ICI all-reduce on a pod.  The sweep runs
# the fused step's pod SPMD mode (MXNET_POD_SPMD=1 default: shard_map
# over dp, bucketed single-psum gradient exchange) — the fast path this
# artifact certifies.
IMG_FEATURES = 512          # a flattened 13x13x3 "image"
IMG_HIDDEN = 1024
IMG_BATCH_PER_DEV = 768
TOK_SEQ = 32                # tokens per sample; tokens/s = samples/s * T
TOK_FEATURES = 512          # flattened 32 x d16 token sequence
TOK_HIDDEN = 1024
TOK_BATCH_PER_DEV = 768
STEPS_PER_EPOCH = 8
EPOCHS = 3                  # epoch 0 pays compiles; 1..2 are the window
FUSED_STEP_BLOCK = 4        # K-step scan block at every point (see _spawn)
POINT_REPEATS = 3           # best-of-R per point: each point is a fresh
                            # subprocess pinned to ndev cores on a SHARED
                            # host, so a noisy-neighbor burst in one run
                            # must not masquerade as a scaling cliff


# ---------------------------------------------------------------------------
# subprocess stage: one scaling point
# ---------------------------------------------------------------------------

def _mlp(d, hidden, n_out, prefix):
    from incubator_mxnet_tpu import sym
    h = sym.FullyConnected(d, num_hidden=hidden, name=prefix + "_fc1")
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, num_hidden=hidden, name=prefix + "_fc2")
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, num_hidden=n_out, name=prefix + "_head")
    return sym.SoftmaxOutput(h, name="softmax")


def _build_image_net():
    from incubator_mxnet_tpu import sym
    return _mlp(sym.Variable("data"), IMG_HIDDEN, 10, "img")


def _build_token_net():
    from incubator_mxnet_tpu import sym                # (B, T*d) tokens
    return _mlp(sym.Variable("data"), TOK_HIDDEN, 16, "tok")


class _StagedIter:
    """NDArrayIter lookalike that feeds PRE-SHARDED device batches,
    staged once at construction (before fit, outside the timed window).
    On a real pod each host stages only its own chips' shard of the
    batch; in this single-process sweep one host would be staging all N
    simulated hosts' data serially, so leaving that funnel inside the
    timed window would charge the SPMD fast path for an artifact of the
    simulation.  The staged batches hit the fused step's already-placed
    path (`_stage_inputs` skips the dispatch when `raw.sharding` matches
    the data sharding) — exactly what `Module.prepare` prefetching
    converges to with a real per-host input pipeline."""

    def __init__(self, X, y, batch, ctxs):
        from incubator_mxnet_tpu.io import NDArrayIter
        self._inner = NDArrayIter(X, y, batch_size=batch, shuffle=False)
        self._X, self._y, self._batch, self._ctxs = X, y, batch, ctxs
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label
        self._staged = self._stage()   # staged BEFORE fit: never timed
        self._pos = 0

    def _stage(self):
        import jax
        import numpy as np
        from jax.sharding import (Mesh, NamedSharding, PartitionSpec,
                                  SingleDeviceSharding)
        from incubator_mxnet_tpu.io import DataBatch
        from incubator_mxnet_tpu.ndarray.ndarray import NDArray
        devs = [c.jax_device for c in self._ctxs]
        if len(devs) > 1:
            sharding = NamedSharding(Mesh(np.array(devs), ("dp",)),
                                     PartitionSpec("dp"))
        else:
            sharding = SingleDeviceSharding(devs[0])
        batches = []
        for s in range(len(self._X) // self._batch):
            lo, hi = s * self._batch, (s + 1) * self._batch
            xb = jax.device_put(self._X[lo:hi], sharding)
            yb = jax.device_put(self._y[lo:hi], sharding)
            batches.append(DataBatch(
                data=[NDArray(xb, ctx=self._ctxs[0])],
                label=[NDArray(yb, ctx=self._ctxs[0])], pad=0))
        return batches

    def reset(self):
        self._pos = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._pos >= len(self._staged):
            raise StopIteration
        b = self._staged[self._pos]
        self._pos += 1
        return b

    next = __next__


def _timed_fit(net, ndev, batch, features, quick):
    """Train through Module.fit on ndev devices; returns
    (samples_per_s, steady_compiles)."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import compile as _compile

    steps = STEPS_PER_EPOCH if not quick else 6
    epochs = EPOCHS
    mx.random.seed(0)
    np.random.seed(0)
    n = batch * steps
    X = np.random.RandomState(2).randn(n, features).astype("f4")
    y = (np.arange(n) % 10).astype("f4")
    ctxs = [mx.cpu(i) for i in range(ndev)] if ndev > 1 else [mx.cpu(0)]
    it = _StagedIter(X, y, batch, ctxs)
    mod = mx.mod.Module(net, context=ctxs if ndev > 1 else ctxs[0])
    # epoch-boundary marks: immune to the K-step block's bursty
    # batch_end callbacks (all K fire after the block executes, so
    # per-batch timestamps cluster and would miscount the window)
    marks = []                       # (epoch, perf_counter, compiles)

    def ecb(epoch, *_):
        marks.append((epoch, time.perf_counter(),
                      _compile.stats()["counters"]["compiles"]))

    mod.fit(it, kvstore="device", optimizer="sgd",
            optimizer_params={"learning_rate": 0.01, "momentum": 0.9},
            num_epoch=epochs, epoch_end_callback=ecb)
    pod = getattr(mod._fused_step, "pod_stats", None) \
        if mod._fused_step is not None else None
    if len(marks) < 2:
        return 0.0, -1, pod
    # epoch 0 pays compiles + placement; the window is epochs 1..end
    dt = marks[-1][1] - marks[0][1]
    samples = (len(marks) - 1) * steps * batch
    steady_compiles = marks[-1][2] - marks[0][2]
    return samples / max(dt, 1e-9), steady_compiles, pod


def _kvstore_economy(ndev, quick):
    """One batched push/pull cycle over a convnet-shaped parameter set:
    the collective store's dispatch economy for this mesh width."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    if ndev < 2:
        return None
    devs = [mx.cpu(i) for i in range(ndev)]
    rng = np.random.RandomState(0)
    # convnet-shaped: a few big tensors, many small ones
    shapes = ([(512, 512)] * 4 + [(512,)] * 4 +
              [(128, 128)] * 8 + [(128,)] * 8 + [(10, 128), (10,)])
    keys = ["p%d" % i for i in range(len(shapes))]
    kv = mx.kv.create("device")
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    vals = [[nd.array(rng.randn(*s).astype("f4"), ctx=d) for d in devs]
            for s in shapes]
    outs = [[nd.zeros(s, ctx=d) for d in devs] for s in shapes]
    steps = 2 if quick else 4
    for _ in range(steps):
        kv.push(keys, vals)
        kv.pull(keys, out=outs)
    st = kv.stats()
    st["params"] = len(keys)
    st["allreduce_dispatches_per_step"] = \
        st["allreduce_dispatches"] / max(1, st["batched_pushes"])
    return st


def _shard_static(ndev):
    """mxshard's static prediction for this point's workloads — per-
    device peak HBM and the per-step dp ICI byte bill — recorded NEXT
    TO the measured pod/kvstore counters, so the artifact itself shows
    whether the static model tracks the machine (the parity sharding
    stage gates the agreement at 10%)."""
    from incubator_mxnet_tpu.analysis import sharding as mxshard
    out = {}
    for name, net, feat, batch in (
            ("img", _build_image_net(), IMG_FEATURES,
             IMG_BATCH_PER_DEV * ndev),
            ("tok", _build_token_net(), TOK_FEATURES,
             TOK_BATCH_PER_DEV * ndev)):
        stats = mxshard.shard_collectives(
            net, shapes={"data": (batch, feat),
                         "softmax_label": (batch,)},
            mesh={"dp": ndev}, name="scaling.%s" % name)
        rep = stats.pop("report")
        dp_plan = stats.get("dp") or {}
        out[name] = {
            "per_device_peak_hbm_bytes": rep.per_device_peak_hbm_bytes,
            "replicated_peak_hbm_bytes": rep.replicated_peak_hbm_bytes,
            "dp_ici_bytes_per_step":
                int(dp_plan.get("bytes_per_step") or 0),
            "dp_collectives_per_step":
                int(dp_plan.get("collectives_per_step") or 0),
        }
    return out


def run_point(ndev, quick):
    img_sps, img_steady, pod = _timed_fit(
        _build_image_net(), ndev, IMG_BATCH_PER_DEV * ndev, IMG_FEATURES,
        quick)
    tok_sps, tok_steady, _ = _timed_fit(
        _build_token_net(), ndev, TOK_BATCH_PER_DEV * ndev, TOK_FEATURES,
        quick)
    point = {
        "devices": ndev,
        "img_per_s": round(img_sps, 1),
        "tokens_per_s": round(tok_sps * TOK_SEQ, 1),
        "steady_compiles": img_steady + tok_steady,
        "pod": pod,
        "kvstore": _kvstore_economy(ndev, quick),
        "shard_static": _shard_static(ndev),
    }
    pt_pod = point["pod"] or {}
    img_static = point["shard_static"]["img"]
    if pt_pod.get("bytes_per_step") and img_static["dp_ici_bytes_per_step"]:
        # measured pod exchange vs mxshard's static plan for the SAME
        # image net: the in-artifact agreement the parity stage gates
        meas = int(pt_pod["bytes_per_step"])
        stat = int(img_static["dp_ici_bytes_per_step"])
        point["shard_static"]["img_agreement_pct"] = round(
            abs(stat - meas) * 100.0 / max(1, meas), 3)
    from incubator_mxnet_tpu import analysis as _analysis
    point["runtime_findings"] = [
        f.message for f in _analysis.runtime_report()
        if f.pass_name == "kvstore.buckets"]
    return point


# ---------------------------------------------------------------------------
# subprocess stage: comm-heavy bucketed-vs-single-bucket A/B
# ---------------------------------------------------------------------------

def run_comm(ndev, quick):
    """The 8-device comm-heavy bench: step throughput of the bucketed
    overlapped path vs the single-bucket `_reduce_many` it replaced
    (cap >= total bytes = one flatten-concat bucket, the old code's
    exact dataflow)."""
    import numpy as np
    import jax
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd

    devs = [mx.cpu(i) for i in range(ndev)]
    rng = np.random.RandomState(0)
    nkeys = 16 if quick else 24
    shapes = [(1024, 512)] * nkeys          # 2 MB per key
    keys = ["g%d" % i for i in range(nkeys)]
    steps = 4 if quick else 8

    def bench(cap_mb, overlap):
        os.environ["MXNET_KVSTORE_BUCKET_MB"] = str(cap_mb)
        os.environ["MXNET_KVSTORE_OVERLAP"] = "1" if overlap else "0"
        kv = mx.kv.create("device")
        for k, s in zip(keys, shapes):
            kv.init(k, nd.zeros(s))
        vals = [[nd.array(rng.randn(*s).astype("f4"), ctx=d)
                 for d in devs] for s in shapes]
        kv.push(keys, vals)                  # pay the compiles
        for k in keys:
            jax.block_until_ready(kv._store[k]._data)
        t0 = time.perf_counter()
        for _ in range(steps):
            kv.push(keys, vals)
        for k in keys:
            jax.block_until_ready(kv._store[k]._data)
        dt = (time.perf_counter() - t0) / steps
        st = kv.stats()
        return {"ms_per_step": round(dt * 1e3, 2),
                "buckets_per_push": st["buckets"] / max(
                    1, st["batched_pushes"]),
                "overlap_ratio": round(st["overlap_ratio"], 3),
                "bucket_fill_hist": st["bucket_fill_hist"]}

    total_mb = sum(int(np.prod(s)) * 4 for s in shapes) >> 20
    single = bench(max(4096, 2 * total_mb), True)    # ONE bucket
    bucketed = bench(4, True)
    bucketed_sync = bench(4, False)
    best = min(bucketed["ms_per_step"], bucketed_sync["ms_per_step"])
    return {
        "devices": ndev,
        "keys": nkeys,
        "total_mb": total_mb,
        "single_bucket": single,
        "bucketed_overlapped": bucketed,
        "bucketed_blocking": bucketed_sync,
        "bucketed_speedup": round(single["ms_per_step"] / max(
            bucketed["ms_per_step"], 1e-9), 2),
        "best_speedup": round(single["ms_per_step"] / max(best, 1e-9), 2),
    }


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------

def _spawn(stage, ndev, platform, quick):
    env = dict(os.environ,
               PYTHONPATH=REPO + os.pathsep +
               os.environ.get("PYTHONPATH", ""))
    # the sweep certifies the FAST path: the fused step's pod SPMD mode
    # (shard_map + bucketed psum exchange, MXNET_POD_SPMD) — on by
    # default; callers can pin it off (or pin MXNET_ZERO=1 for the
    # GSPMD weight-update-sharding lowering) for A/B runs
    env.setdefault("MXNET_POD_SPMD", "1")
    # K-step scan blocks at EVERY point (same config at every width —
    # honest weak scaling): per-step Python dispatch is fixed overhead
    # that the wide points cannot hide behind compute the way dp=1 can,
    # so amortizing it across K steps is part of the fast path the
    # artifact certifies (recorded as `fused_step_block`)
    env.setdefault("MXNET_FUSED_STEP_BLOCK", str(FUSED_STEP_BLOCK))
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        import re as _re
        flags = _re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                        flags)
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=%d"
                            % ndev).strip()
    cmd = [sys.executable, os.path.abspath(__file__), stage, str(ndev)]
    if quick:
        cmd.append("--quick")
    out = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                         text=True, timeout=1200)
    for line in out.stdout.splitlines():
        if line.startswith("RESULT "):
            return json.loads(line[len("RESULT "):])
    raise RuntimeError("scaling %s dp=%d failed rc=%d: %s" % (
        stage, ndev, out.returncode,
        (out.stdout + out.stderr).strip()[-800:]))


def main(argv=None):
    ap = argparse.ArgumentParser(prog="run_scaling", description=__doc__)
    ap.add_argument("--devices", default="1,2,4,8")
    ap.add_argument("--platform", default="cpu", choices=("cpu", "tpu"))
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--out", default=None)
    ap.add_argument("--point", type=int, default=None)
    ap.add_argument("--comm", type=int, default=None)
    args, extra = ap.parse_known_args(argv)

    # internal subprocess stages (positional compat: "--point 4" spawn
    # builds "point 4")
    if extra and extra[0] in ("point", "comm"):
        args.point = int(extra[1]) if extra[0] == "point" else None
        args.comm = int(extra[1]) if extra[0] == "comm" else None
    if args.point is not None or args.comm is not None:
        sys.path.insert(0, REPO)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        ndev_stage = args.point if args.point is not None else args.comm
        if os.environ.get("JAX_PLATFORMS") == "cpu" and \
                hasattr(os, "sched_setaffinity"):
            # one host core per virtual device, for EVERY point: the
            # honest weak-scaling control (dp=1 on one core, dp=8 on
            # eight) — without it the dp=1 baseline monopolizes the
            # whole multi-core host and the curve measures the host's
            # thread scheduler, not the scaling machinery
            try:
                os.sched_setaffinity(
                    0, set(range(min(ndev_stage, os.cpu_count() or 1))))
            except OSError:
                pass
        if args.point is not None:
            result = run_point(args.point, args.quick)
        else:
            result = run_comm(args.comm, args.quick)
        print("RESULT " + json.dumps(result))
        return 0

    devices = sorted({int(d) for d in args.devices.split(",") if d})
    out_path = args.out or os.path.join(REPO, "BENCH_SCALING.json")
    t0 = time.time()
    points = []
    for nd_ in devices:
        reps = [_spawn("point", nd_, args.platform, args.quick)
                for _ in range(POINT_REPEATS)]
        # per-sub-bench best repeat (img and tokens are independent
        # fits); steady_compiles takes the MAX so a recompile in ANY
        # repeat fails the zero-recompile gate
        pt = max(reps, key=lambda p: p["img_per_s"])
        pt["img_per_s"] = max(p["img_per_s"] for p in reps)
        pt["tokens_per_s"] = max(p["tokens_per_s"] for p in reps)
        pt["steady_compiles"] = max(p["steady_compiles"] for p in reps)
        pt["repeats"] = POINT_REPEATS
        points.append(pt)
        if not args.as_json:
            print("scaling[dp=%d]: %.0f img/s  %.0f tokens/s  "
                  "steady_compiles=%d" %
                  (nd_, pt["img_per_s"], pt["tokens_per_s"],
                   pt["steady_compiles"]), file=sys.stderr)
    comm = _spawn("comm", max(devices), args.platform, args.quick)
    if not args.as_json:
        print("scaling[comm dp=%d]: single=%.0fms bucketed=%.0fms "
              "speedup=%.2fx" %
              (comm["devices"], comm["single_bucket"]["ms_per_step"],
               comm["bucketed_overlapped"]["ms_per_step"],
               comm["bucketed_speedup"]), file=sys.stderr)

    base = points[0]
    for pt in points:
        n = pt["devices"] / base["devices"]
        pt["img_efficiency"] = round(
            pt["img_per_s"] / max(base["img_per_s"] * n, 1e-9), 3)
        pt["tokens_efficiency"] = round(
            pt["tokens_per_s"] / max(base["tokens_per_s"] * n, 1e-9), 3)
    top = points[-1]
    kv_top = top.get("kvstore") or {}
    gates = {
        "dp%d_efficiency_ge_0.8" % top["devices"]:
            top["img_efficiency"] >= 0.8,
        "bucketed_speedup_ge_1.15": comm["bucketed_speedup"] >= 1.15,
        "zero_steady_state_recompiles":
            all(pt["steady_compiles"] == 0 for pt in points),
        "dispatches_O_buckets": bool(kv_top) and
            kv_top["allreduce_dispatches_per_step"] < kv_top["params"] / 2,
    }
    artifact = {
        "platform": args.platform,
        "quick": args.quick,
        "per_device_batch": {"img": IMG_BATCH_PER_DEV,
                             "tokens": TOK_BATCH_PER_DEV},
        "fused_step_block": int(os.environ.get(
            "MXNET_FUSED_STEP_BLOCK", FUSED_STEP_BLOCK)),
        "points": points,
        "comm": comm,
        "gates": gates,
        "all_passed": all(gates.values()),
        "duration_s": round(time.time() - t0, 1),
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)
    if args.as_json:
        print(json.dumps(artifact))
    else:
        print("scaling: %d point(s), gates=%s -> %s" %
              (len(points), gates, out_path))
    return 0 if artifact["all_passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
