#!/usr/bin/env python
"""Build .lst / .rec image databases (reference `tools/im2rec.py` +
`tools/im2rec.cc`): list mode walks an image directory into a
`index\\tlabel\\tpath` .lst file; pack mode encodes the listed images into
an indexed RecordIO pair (.rec + .idx) the `ImageRecordIter` consumes.

The byte format is the reference's exactly (recordio.pack_img headers),
so .rec files interchange in both directions.  Threaded encode: cv2
decode/encode releases the GIL, so --num-thread scales on multi-core
hosts (the reference uses a process pool for the same reason).
"""
from __future__ import annotations

import argparse
import os
import queue
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def list_image(root, recursive, exts):
    """Yield (index, relpath, label) — label = folder index in recursive
    mode (the reference's convention), 0 otherwise."""
    i = 0
    if recursive:
        cat = {}
        for path, dirs, files in sorted(os.walk(root, followlinks=True)):
            dirs.sort()
            files.sort()
            for fname in files:
                fpath = os.path.join(path, fname)
                suffix = os.path.splitext(fname)[1].lower()
                if os.path.isfile(fpath) and suffix in exts:
                    if path not in cat:
                        cat[path] = len(cat)
                    yield (i, os.path.relpath(fpath, root), cat[path])
                    i += 1
        for k, v in sorted(cat.items(), key=lambda kv: kv[1]):
            print(os.path.relpath(k, root), v)
    else:
        for fname in sorted(os.listdir(root)):
            fpath = os.path.join(root, fname)
            suffix = os.path.splitext(fname)[1].lower()
            if os.path.isfile(fpath) and suffix in exts:
                yield (i, fname, 0)
                i += 1


def write_list(path_out, image_list):
    with open(path_out, "w") as fout:
        for i, item in enumerate(image_list):
            line = "%d\t" % item[0]
            for j in item[2:]:
                line += "%f\t" % j
            line += "%s\n" % item[1]
            fout.write(line)


def make_list(args):
    image_list = list(list_image(args.root, args.recursive, args.exts))
    if args.shuffle:
        random.seed(100)
        random.shuffle(image_list)
    n = len(image_list)
    chunk_size = (n + args.chunks - 1) // args.chunks
    for i in range(args.chunks):
        chunk = image_list[i * chunk_size:(i + 1) * chunk_size]
        str_chunk = "_%d" % i if args.chunks > 1 else ""
        sep = int(chunk_size * args.train_ratio)
        sep_test = int(chunk_size * args.test_ratio)
        if args.train_ratio == 1.0:
            write_list(args.prefix + str_chunk + ".lst", chunk)
        else:
            if args.test_ratio:
                write_list(args.prefix + str_chunk + "_test.lst",
                           chunk[:sep_test])
            if args.train_ratio + args.test_ratio < 1.0:
                write_list(args.prefix + str_chunk + "_val.lst",
                           chunk[sep_test + sep:])
            write_list(args.prefix + str_chunk + "_train.lst",
                       chunk[sep_test:sep_test + sep])


def read_list(path_in):
    with open(path_in) as fin:
        while True:
            line = fin.readline()
            if not line:
                break
            line = [i.strip() for i in line.strip().split("\t")]
            line_len = len(line)
            if line_len < 3:
                print("lst should have at least has three parts, but only "
                      "has %s parts for %s" % (line_len, line))
                continue
            try:
                item = [int(line[0])] + [line[-1]] + \
                    [float(i) for i in line[1:-1]]
            except Exception as e:
                print("Parsing lst met error for %s, detail: %s"
                      % (line, e))
                continue
            yield item


def image_encode(args, i, item, q_out):
    """Read + (resize/crop) + encode one image; enqueue the packed record."""
    import cv2
    from incubator_mxnet_tpu import recordio

    fullpath = os.path.join(args.root, item[1])
    if len(item) > 3 and args.pack_label:
        header = recordio.IRHeader(0, item[2:], item[0], 0)
    else:
        header = recordio.IRHeader(0, item[2], item[0], 0)

    if args.pass_through:
        try:
            with open(fullpath, "rb") as fin:
                img = fin.read()
            s = recordio.pack(header, img)
            q_out.put((i, s, item))
        except Exception as e:
            print("pack_img error:", item[1], e)
            q_out.put((i, None, item))
        return

    flag = {1: cv2.IMREAD_COLOR, 0: cv2.IMREAD_GRAYSCALE,
            -1: cv2.IMREAD_UNCHANGED}[args.color]
    img = cv2.imread(fullpath, flag)
    if img is None:
        print("imread read blank (None) image for file: %s" % fullpath)
        q_out.put((i, None, item))
        return
    if args.center_crop:
        if img.shape[0] > img.shape[1]:
            margin = (img.shape[0] - img.shape[1]) // 2
            img = img[margin:margin + img.shape[1], :]
        else:
            margin = (img.shape[1] - img.shape[0]) // 2
            img = img[:, margin:margin + img.shape[0]]
    if args.resize:
        import cv2 as _cv2
        if img.shape[0] > img.shape[1]:
            newsize = (args.resize,
                       img.shape[0] * args.resize // img.shape[1])
        else:
            newsize = (img.shape[1] * args.resize // img.shape[0],
                       args.resize)
        img = _cv2.resize(img, newsize)
    try:
        from incubator_mxnet_tpu import recordio as _rec
        s = _rec.pack_img(header, img, quality=args.quality,
                          img_fmt=args.encoding)
        q_out.put((i, s, item))
    except Exception as e:
        print("pack_img error on file: %s" % fullpath, e)
        q_out.put((i, None, item))


def make_record(args, lst_path):
    """Pack one .lst into .rec + .idx with a thread pool + in-order
    writer (the reference's read_worker/write_worker shape)."""
    from incubator_mxnet_tpu import recordio

    items = list(read_list(lst_path))
    fname = os.path.basename(lst_path)
    base = os.path.splitext(fname)[0]
    rec_path = os.path.join(args.working_dir or os.path.dirname(lst_path),
                            base + ".rec")
    idx_path = os.path.join(args.working_dir or os.path.dirname(lst_path),
                            base + ".idx")
    record = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")

    q_out = queue.Queue(maxsize=args.num_thread * 8)
    job_q = queue.Queue()
    for i, item in enumerate(items):
        job_q.put((i, item))

    def worker():
        while True:
            try:
                i, item = job_q.get_nowait()
            except queue.Empty:
                return
            try:
                image_encode(args, i, item, q_out)
            except Exception as e:
                # the writer loop blocks on one sentinel per job: a dead
                # worker without this enqueue would hang the tool forever
                print("encode error on %s: %r" % (item[1], e))
                q_out.put((i, None, item))

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(args.num_thread, 1))]
    for t in threads:
        t.start()

    tic = time.time()
    buf = {}
    count = 0
    for _ in range(len(items)):
        i, s, item = q_out.get()
        buf[i] = (s, item)
        while count in buf:
            s2, item2 = buf.pop(count)
            if s2 is not None:
                record.write_idx(item2[0], s2)
            if count % 1000 == 0 and count > 0:
                print("time: %f count: %d" % (time.time() - tic, count))
                tic = time.time()
            count += 1
    record.close()
    print("wrote %d records to %s" % (count, rec_path))


def parse_args():
    parser = argparse.ArgumentParser(
        description="Create an image list or an indexed RecordIO database "
                    "(reference tools/im2rec.py).")
    parser.add_argument("prefix",
                        help="prefix of input/output lst and rec files")
    parser.add_argument("root", help="path to folder containing images")
    cgroup = parser.add_argument_group("Options for creating image lists")
    cgroup.add_argument("--list", action="store_true",
                        help="make a list instead of a record")
    cgroup.add_argument("--exts", nargs="+",
                        default=[".jpeg", ".jpg", ".png"])
    cgroup.add_argument("--chunks", type=int, default=1)
    cgroup.add_argument("--train-ratio", type=float, default=1.0)
    cgroup.add_argument("--test-ratio", type=float, default=0)
    cgroup.add_argument("--recursive", action="store_true",
                        help="label = folder index, walked recursively")
    cgroup.add_argument("--no-shuffle", dest="shuffle",
                        action="store_false")
    rgroup = parser.add_argument_group("Options for creating database")
    rgroup.add_argument("--pass-through", action="store_true",
                        help="skip transcoding; pack raw file bytes")
    rgroup.add_argument("--resize", type=int, default=0)
    rgroup.add_argument("--center-crop", action="store_true")
    rgroup.add_argument("--quality", type=int, default=95)
    rgroup.add_argument("--num-thread", type=int, default=1)
    rgroup.add_argument("--color", type=int, default=1, choices=[-1, 0, 1])
    rgroup.add_argument("--encoding", type=str, default=".jpg",
                        choices=[".jpg", ".png"])
    rgroup.add_argument("--pack-label", action="store_true",
                        help="pack multi-label from the lst")
    rgroup.add_argument("--working-dir", type=str, default=None)
    return parser.parse_args()


def main():
    args = parse_args()
    if args.list:
        make_list(args)
        return
    d = os.path.dirname(os.path.abspath(args.prefix))
    files = [os.path.join(d, f) for f in os.listdir(d or ".")
             if f.startswith(os.path.basename(args.prefix)) and
             f.endswith(".lst")]
    if not files:
        print("no .lst files found with prefix %s; run --list first"
              % args.prefix)
        sys.exit(1)
    for lst in sorted(files):
        print("Creating .rec file from", lst)
        make_record(args, lst)


if __name__ == "__main__":
    main()
