#!/usr/bin/env python
"""Build .lst / .rec image databases.

Same CLI and byte formats as the classic tool (list mode emits
``index\\tlabel...\\tpath`` .lst files; pack mode emits an indexed RecordIO
pair the `ImageRecordIter` consumes, headers via `recordio.pack_img`, so
.rec files interchange in both directions) — implementation is this
repo's own: a scandir-based walker, numpy-seeded deterministic shuffling,
and a ThreadPoolExecutor encode pool with an in-order writer (cv2
releases the GIL, so threads scale across cores without the process-pool
plumbing).
"""
from __future__ import annotations

import argparse
import os
import sys
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_SHUFFLE_SEED = 100   # classic tool contract: same inputs -> same listing


def scan_images(root, recursive, exts):
    """[(index, relpath, label)] under `root`; in recursive mode the label
    is the sorted-walk folder index (returned as the second element)."""
    exts = {e.lower() for e in exts}

    def keep(entry):
        return entry.is_file() and \
            os.path.splitext(entry.name)[1].lower() in exts

    rows, categories = [], {}
    if not recursive:
        with os.scandir(root) as it:
            names = sorted(e.name for e in it if keep(e))
        rows = [(i, name, 0) for i, name in enumerate(names)]
        return rows, categories

    stack = [root]
    while stack:
        here = stack.pop()
        subdirs, files = [], []
        with os.scandir(here) as it:
            for entry in it:
                if entry.is_dir(follow_symlinks=True):
                    subdirs.append(entry.path)
                elif keep(entry):
                    files.append(entry.path)
        # depth-first in reverse-sorted stack order == sorted overall walk
        stack.extend(sorted(subdirs, reverse=True))
        if files:
            label = categories.setdefault(os.path.relpath(here, root),
                                          len(categories))
            rows.extend((0, os.path.relpath(f, root), label)
                        for f in sorted(files))
    rows = [(i, rel, label) for i, (_, rel, label) in enumerate(rows)]
    return rows, categories


def write_listing(path, rows):
    """One ``index\\tlabel...\\tpath`` line per row — the .lst byte format
    every consumer of the classic tool expects (labels as %f)."""
    with open(path, "w") as out:
        for row in rows:
            labels = "".join("%f\t" % field for field in row[2:])
            out.write("%d\t%s%s\n" % (row[0], labels, row[1]))


def build_lists(args):
    rows, categories = scan_images(args.root, args.recursive, args.exts)
    for name, label in sorted(categories.items(), key=lambda kv: kv[1]):
        print(name, label)
    if args.shuffle:
        order = np.random.RandomState(_SHUFFLE_SEED).permutation(len(rows))
        rows = [rows[i] for i in order]
    per_chunk = (len(rows) + args.chunks - 1) // max(args.chunks, 1)
    for c in range(args.chunks):
        chunk = rows[c * per_chunk:(c + 1) * per_chunk]
        tag = "_%d" % c if args.chunks > 1 else ""
        n_test = int(per_chunk * args.test_ratio)
        n_train = int(per_chunk * args.train_ratio)
        if args.train_ratio == 1.0:
            write_listing(args.prefix + tag + ".lst", chunk)
            continue
        if n_test:
            write_listing(args.prefix + tag + "_test.lst", chunk[:n_test])
        write_listing(args.prefix + tag + "_train.lst",
                      chunk[n_test:n_test + n_train])
        if args.train_ratio + args.test_ratio < 1.0:
            write_listing(args.prefix + tag + "_val.lst",
                          chunk[n_test + n_train:])


def parse_listing(path):
    """Rows back out of a .lst: (index, relpath, label...).  Malformed
    lines are reported and dropped, never fatal — a million-image listing
    should not die on one bad row."""
    rows = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            fields = [t.strip() for t in line.rstrip("\n").split("\t")]
            if len(fields) < 3:
                print("%s:%d: expected 'index\\tlabel\\tpath', got %r — "
                      "skipped" % (path, lineno, line.rstrip()))
                continue
            try:
                rows.append([int(float(fields[0])), fields[-1]] +
                            [float(t) for t in fields[1:-1]])
            except ValueError as exc:
                print("%s:%d: unparseable row (%s) — skipped"
                      % (path, lineno, exc))
    return rows


def _square_center(img):
    h, w = img.shape[:2]
    side = min(h, w)
    top, left = (h - side) // 2, (w - side) // 2
    return img[top:top + side, left:left + side]


def load_and_encode(args, row):
    """One listing row -> packed record bytes, or None on a bad image."""
    import cv2
    from incubator_mxnet_tpu import recordio

    path = os.path.join(args.root, row[1])
    label = row[2:] if (args.pack_label and len(row) > 3) else row[2]
    header = recordio.IRHeader(0, label, row[0], 0)

    if args.pass_through:
        try:
            with open(path, "rb") as f:
                return recordio.pack(header, f.read())
        except OSError as exc:
            print("cannot read %s: %s" % (path, exc))
            return None

    modes = {1: cv2.IMREAD_COLOR, 0: cv2.IMREAD_GRAYSCALE,
             -1: cv2.IMREAD_UNCHANGED}
    img = cv2.imread(path, modes[args.color])
    if img is None:
        print("cannot decode %s — skipped" % path)
        return None
    if args.center_crop:
        img = _square_center(img)
    if args.resize and min(img.shape[:2]) != args.resize:
        h, w = img.shape[:2]
        scale = args.resize / min(h, w)
        img = cv2.resize(img, (max(1, round(w * scale)),
                               max(1, round(h * scale))))
    try:
        return recordio.pack_img(header, img, quality=args.quality,
                                 img_fmt=args.encoding)
    except Exception as exc:
        print("encode failed for %s: %r" % (path, exc))
        return None


def pack_records(args, lst_path):
    """Encode one listing into .rec + .idx: a thread pool races ahead on
    decode/encode while the single writer commits records in listing
    order (the index must match the .lst)."""
    from incubator_mxnet_tpu import recordio

    rows = parse_listing(lst_path)
    out_dir = args.working_dir or os.path.dirname(lst_path)
    stem = os.path.splitext(os.path.basename(lst_path))[0]
    writer = recordio.MXIndexedRecordIO(os.path.join(out_dir, stem + ".idx"),
                                        os.path.join(out_dir, stem + ".rec"),
                                        "w")
    written = 0
    tic = time.time()
    threads = max(args.num_thread, 1)
    # bounded submission window: encoders may run at most window records
    # ahead of the in-order writer, so a slow disk never lets a million
    # encoded JPEGs pile up in RAM
    window = threads * 8
    pending = deque()

    def drain_one():
        nonlocal written, tic
        row, future = pending.popleft()
        try:
            packed = future.result()
        except Exception as exc:
            # one undecodable/oversized image must never abort a
            # million-image pack — report it and keep writing
            print("skipping %s: %r" % (row[1], exc))
            packed = None
        if packed is not None:
            writer.write_idx(row[0], packed)
            written += 1
            if written % 1000 == 0:
                print("packed %d records (%.1fs)" % (written,
                                                     time.time() - tic))
                tic = time.time()

    with ThreadPoolExecutor(max_workers=threads) as pool:
        for row in rows:
            pending.append((row, pool.submit(load_and_encode, args, row)))
            if len(pending) >= window:
                drain_one()
        while pending:
            drain_one()
    writer.close()
    print("wrote %d records to %s" % (written,
                                      os.path.join(out_dir, stem + ".rec")))


def parse_args():
    parser = argparse.ArgumentParser(
        description="Create an image list or an indexed RecordIO database.")
    parser.add_argument("prefix",
                        help="prefix of input/output lst and rec files")
    parser.add_argument("root", help="path to folder containing images")
    cgroup = parser.add_argument_group("Options for creating image lists")
    cgroup.add_argument("--list", action="store_true",
                        help="make a list instead of a record")
    cgroup.add_argument("--exts", nargs="+",
                        default=[".jpeg", ".jpg", ".png"])
    cgroup.add_argument("--chunks", type=int, default=1)
    cgroup.add_argument("--train-ratio", type=float, default=1.0)
    cgroup.add_argument("--test-ratio", type=float, default=0)
    cgroup.add_argument("--recursive", action="store_true",
                        help="label = folder index, walked recursively")
    cgroup.add_argument("--no-shuffle", dest="shuffle",
                        action="store_false")
    rgroup = parser.add_argument_group("Options for creating database")
    rgroup.add_argument("--pass-through", action="store_true",
                        help="skip transcoding; pack raw file bytes")
    rgroup.add_argument("--resize", type=int, default=0)
    rgroup.add_argument("--center-crop", action="store_true")
    rgroup.add_argument("--quality", type=int, default=95)
    rgroup.add_argument("--num-thread", type=int, default=1)
    rgroup.add_argument("--color", type=int, default=1, choices=[-1, 0, 1])
    rgroup.add_argument("--encoding", type=str, default=".jpg",
                        choices=[".jpg", ".png"])
    rgroup.add_argument("--pack-label", action="store_true",
                        help="pack multi-label from the lst")
    rgroup.add_argument("--working-dir", type=str, default=None)
    return parser.parse_args()


def main():
    args = parse_args()
    if args.list:
        build_lists(args)
        return 0
    base_dir = os.path.dirname(os.path.abspath(args.prefix)) or "."
    stem = os.path.basename(args.prefix)
    listings = sorted(os.path.join(base_dir, name)
                      for name in os.listdir(base_dir)
                      if name.startswith(stem) and name.endswith(".lst"))
    if not listings:
        print("no .lst files match prefix %r — generate one with --list"
              % args.prefix)
        return 1
    for lst in listings:
        print("packing", lst)
        pack_records(args, lst)
    return 0


if __name__ == "__main__":
    sys.exit(main())
