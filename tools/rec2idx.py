#!/usr/bin/env python
"""Rebuild the .idx for an existing .rec (reference `tools/rec2idx.py`):
scans the RecordIO framing and writes `key\\toffset` lines so
`MXIndexedRecordIO` can random-access / shard the file."""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_index(rec_path, idx_path):
    from incubator_mxnet_tpu import recordio

    reader = recordio.MXRecordIO(rec_path, "r")
    with open(idx_path, "w") as fidx:
        counter = 0
        while True:
            pos = reader.tell()
            item = reader.read()
            if item is None:
                break
            try:
                header, _ = recordio.unpack(item)
                key = int(header.id)
            except Exception:
                key = counter
            fidx.write("%d\t%d\n" % (key, pos))
            counter += 1
    reader.close()
    return counter


def main():
    ap = argparse.ArgumentParser(
        description="Generate the index file of an existing RecordIO file")
    ap.add_argument("record", help="path to the .rec file")
    ap.add_argument("index", nargs="?", default=None,
                    help="output .idx path (default: .rec with .idx)")
    args = ap.parse_args()
    idx = args.index or os.path.splitext(args.record)[0] + ".idx"
    n = build_index(args.record, idx)
    print("wrote %d index entries to %s" % (n, idx))


if __name__ == "__main__":
    main()
