#!/usr/bin/env python
"""Run the on-chip registry parity battery (tests_tpu/) and commit the
evidence: a per-round ``TPU_PARITY_r<NN>.json`` artifact with pass/fail/
skip counts, per-test outcomes, the git revision, and the backend that
actually ran — so on-chip parity claims are checkable artifacts in the
repo, not commit-message assertions.

Usage: python tools/run_tpu_parity.py [round_number]

Without an argument the round auto-increments past the highest committed
``TPU_PARITY_r*.json``.  The artifact is written even when the battery
fails — a red round is evidence too.
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_ARTIFACT_RE = re.compile(r"^TPU_PARITY_r(\d+)\.json$")


def next_round():
    rounds = [int(m.group(1)) for name in os.listdir(REPO)
              if (m := _ARTIFACT_RE.match(name))]
    return max(rounds, default=0) + 1


def git_revision():
    try:
        out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             cwd=REPO, capture_output=True, text=True,
                             timeout=10)
        return out.stdout.strip() or None
    except Exception:
        return None


def probe_backend():
    """Backend/device census from a throwaway process (importing jax here
    would pin THIS process's platform before pytest gets a say)."""
    probe = ("import jax, json; "
             "print(json.dumps({'backend': jax.default_backend(), "
             "'device_count': jax.device_count(), "
             "'device_kind': jax.devices()[0].device_kind}))")
    try:
        out = subprocess.run([sys.executable, "-c", probe], cwd=REPO,
                             capture_output=True, text=True, timeout=120)
        return json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as exc:
        return {"error": f"backend probe failed: {exc!r}"}


def parse_outcomes(output):
    """Counts + per-test outcomes from a ``-q -rA`` pytest run."""
    counts = {"passed": 0, "failed": 0, "skipped": 0, "errors": 0}
    words = {"passed": "passed", "failed": "failed", "skipped": "skipped",
             "errors": "errors?"}
    for key, word in words.items():
        m = re.search(r"(\d+) %s\b" % word, output)
        if m:
            counts[key] = int(m.group(1))
    tests = []
    for line in output.splitlines():
        m = re.match(r"^(PASSED|FAILED|ERROR|SKIPPED|XFAIL|XPASS)\s+(\S+)",
                     line)
        if m:
            tests.append({"outcome": m.group(1).lower(),
                          "test": m.group(2)})
    return counts, tests


def mxlint_stage():
    """Static-analysis stage: run tools/mxlint.py over examples/ in a
    throwaway process and return its JSON summary (finding counts per
    pass/code) for the round artifact — graph-hygiene regressions become
    checkable evidence next to the parity outcomes."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "mxlint.py"),
           os.path.join(REPO, "examples"), "--json"]
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=600)
        summary = json.loads(out.stdout)
        summary.pop("items", None)   # counts are the artifact; findings
        summary["rc"] = out.returncode  # themselves live in the lint run
        return summary
    except Exception as exc:
        return {"error": f"mxlint stage failed: {exc!r}"}


def cost_stage():
    """Static-cost stage: `mxlint --cost-report` over the canonical
    bench program set, gated against the committed COST_BUDGETS.json
    baseline in a throwaway process.  The artifact records per-program
    flops/bytes/peak-HBM and the per-metric deltas vs budget, so a new
    dequant chain, f32 upcast, extra collective, +bytes/step or
    +peak-HBM is a hard stage failure (rc=1) — cost regressions become
    checkable evidence next to the parity outcomes, BEFORE any bench
    run measures them."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "mxlint.py"),
           "--cost-report", "--json", "--fail-on=warn",
           "--budgets", os.path.join(REPO, "COST_BUDGETS.json")]
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=900)
        summary = json.loads(out.stdout)
        for prog in summary.get("programs", {}).values():
            prog.pop("top_ops", None)     # per-op detail lives in the
            prog.pop("findings", None)    # lint run, not the artifact
        summary["rc"] = out.returncode
        summary["clean"] = out.returncode == 0
        return summary
    except Exception as exc:
        return {"error": f"cost stage failed: {exc!r}"}


def sharding_stage():
    """Static-sharding stage: `mxlint --shard-report` over the bench
    program set under the dp=2,tp=2 mesh, gated against the committed
    COST_BUDGETS.json "sharding" section AND cross-checked against a
    real KVStore push (--measured) in a throwaway process.  The
    artifact records per-program per-device peak HBM, the per-step ICI
    byte bill, the budget deltas, and the static-vs-measured agreement,
    so a new hidden reshard, a silently-replicated matrix param, a
    rule-coverage gap, or a static plan that drifts >10% from the
    measured collective counters is a hard stage failure (rc=1)."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "mxlint.py"),
           "--shard-report", "--json", "--fail-on=warn", "--measured",
           "--budgets", os.path.join(REPO, "COST_BUDGETS.json")]
    env = dict(os.environ)
    if "XLA_FLAGS" not in env:
        # the measured cross-check needs >1 device; on a CPU host that
        # means the forced-host-platform census the test suite uses
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=900, env=env)
        summary = json.loads(out.stdout)
        for prog in summary.get("programs", {}).values():
            prog.pop("findings", None)    # findings live in the lint
            prog.pop("fallback_ops", None)  # run, not the artifact
        summary["rc"] = out.returncode
        meas = summary.get("measured") or {}
        summary["clean"] = (out.returncode == 0 and
                            bool(meas.get("ok", False)) and
                            float(meas.get("agreement_pct") or 0.0)
                            <= 10.0)
        return summary
    except Exception as exc:
        return {"error": f"sharding stage failed: {exc!r}"}


def serving_stage():
    """Serving-bench stage: run tools/run_serving_bench.py --quick in a
    throwaway process and attach its JSON artifact (QPS, p50/p99, batch
    occupancy per offered load, post-warmup recompile count) to the
    round — serving-performance regressions become checkable evidence
    next to the parity outcomes, mirroring the mxlint stage."""
    cmd = [sys.executable, os.path.join(REPO, "tools",
                                        "run_serving_bench.py"),
           "--quick", "--json"]
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=900)
        if out.returncode != 0:
            return {"error": "serving bench rc=%d" % out.returncode,
                    "tail": out.stderr.strip()[-500:]}
        return json.loads(out.stdout)
    except Exception as exc:
        return {"error": f"serving stage failed: {exc!r}"}


def chaos_stage():
    """Fault-injection stage: run tools/run_chaos.py --quick in a
    throwaway process — the tier-1 dist + serving tests under three
    seeded fault schedules — and attach its JSON artifact (faults fired,
    retries, reconnects, pass/fail per schedule) to the round, so the
    resilience layer's recovery claims are checkable evidence next to
    the parity outcomes."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_chaos.py"),
           "--quick", "--json", "--out", ""]
    try:
        # budget: every schedule may legitimately use run_chaos's full
        # per-schedule pytest timeout under heavy injected latency
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=3900)
        summary = json.loads(out.stdout)
        summary["rc"] = out.returncode
        return summary
    except Exception as exc:
        return {"error": f"chaos stage failed: {exc!r}"}


def chaos_pod_stage():
    """Elastic pod stage: run tools/run_chaos.py --pod in a throwaway
    process — three supervised workers mid-fit under heartbeat drops,
    one SIGKILLed host (shrink-and-resume), and one hung collective —
    and attach its CHAOS_POD artifact, including every survivor's
    `JobSupervisor.stats()` dict (heartbeats, watchdog timeouts, hosts
    lost, kvstore retry/breaker counters), to the round.  Pod-level
    recovery claims become checkable evidence next to the parity
    outcomes."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_chaos.py"),
           "--pod", "--json", "--out", ""]
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=1800)
        summary = json.loads(out.stdout)
        summary["rc"] = out.returncode
        return summary
    except Exception as exc:
        return {"error": f"chaos pod stage failed: {exc!r}"}


def chaos_serving_stage():
    """Multi-replica serving stage: run tools/run_chaos.py --serving in
    a throwaway process — a real 3-replica router fleet under a
    SIGKILLed worker, a probe-drop burst, a rolling weight-swap, and a
    torn swap — and attach its CHAOS_SERVING artifact (per-schedule
    checks: zero lost, zero duplicate executions, no false eviction,
    zero-compile spin-up and swap) to the round.  The serving
    availability claims become checkable evidence next to the parity
    outcomes."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_chaos.py"),
           "--serving", "--json", "--out", ""]
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=1800)
        summary = json.loads(out.stdout)
        summary["rc"] = out.returncode
        return summary
    except Exception as exc:
        return {"error": f"chaos serving stage failed: {exc!r}"}


def chaos_fleet_stage():
    """Cross-host fleet stage: run tools/run_chaos.py --fleet in a
    throwaway process — a 2-host fleet (real `serving.hostd` process
    groups, two replicas each) under mixed-priority load with one host
    SIGKILLed mid-ramp — and attach its CHAOS_FLEET artifact (zero
    admitted interactive requests lost, interactive p99 inside its SLO
    band while best-effort sheds first, the fleet backfilled to target
    on the survivor, every backfill spinup certified zero-compile) to
    the round.  Host-loss survival claims become checkable evidence
    next to the parity outcomes."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_chaos.py"),
           "--fleet", "--json", "--out", ""]
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=1800)
        summary = json.loads(out.stdout)
        summary["rc"] = out.returncode
        return summary
    except Exception as exc:
        return {"error": f"chaos fleet stage failed: {exc!r}"}


def chaos_train_stage():
    """Training-guardian stage: run tools/run_chaos.py --train in a
    throwaway process — an injected non-finite gradient (in-graph
    skip-batch, deterministic continuation), an injected loss spike
    (rollback-to-last-good, bit-identical params vs a clean reference),
    and an injected corrupt record (substituted, counted, quarantined,
    skipped on resume) — and attach its CHAOS_TRAIN artifact, each
    recovery certified with zero unified-program-cache compiles.
    Numerical-health recovery claims become checkable evidence next to
    the parity outcomes."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_chaos.py"),
           "--train", "--json", "--out", ""]
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=1800)
        summary = json.loads(out.stdout)
        summary["rc"] = out.returncode
        return summary
    except Exception as exc:
        return {"error": f"chaos train stage failed: {exc!r}"}


def tsan_stage():
    """Concurrency-sanitizer stage: a tier-1-representative subset
    (the tsan fixtures + zero-FP gate + the router battery) runs in a
    throwaway process under ``MXNET_TSAN=1`` with ``MXNET_TSAN_LOG``
    pointed at a scratch artifact; afterwards ``mxlint --tsan-report``
    sweeps the package with the concurrency AST lints and renders the
    runtime dump.  The stage's contract is **zero findings**: seeded
    fixtures assert their own findings and then reset, so anything left
    in the dump is a real lock-order cycle, race, blocking-under-lock,
    or leaked thread in the production code paths the subset drove."""
    import tempfile
    log = os.path.join(tempfile.mkdtemp(prefix="mxtsan_"), "tsan.json")
    env = dict(os.environ, MXNET_TSAN="1", MXNET_TSAN_LOG=log,
               JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "pytest", "tests/test_tsan.py",
           "tests/test_router.py", "-q", "-m", "not slow",
           "-p", "no:cacheprovider"]
    out = {"cmd": " ".join(cmd[2:])}
    try:
        proc = subprocess.run(cmd, cwd=REPO, capture_output=True,
                              text=True, timeout=1800, env=env)
        out["rc"] = proc.returncode
        tail = (proc.stdout + proc.stderr).strip().splitlines()
        out["tail"] = "\n".join(tail[-3:])[-500:]
    except Exception as exc:
        return {"error": f"tsan stage failed: {exc!r}"}
    try:
        with open(log) as f:
            dumps = [json.loads(ln) for ln in f.read().splitlines()
                     if ln.strip()]
        found = [fi for d in dumps for fi in d.get("findings", [])]
        out["processes"] = len(dumps)
        out["runtime_findings"] = len(found)
        out["findings"] = [
            {k: fi.get(k) for k in ("code", "severity", "location")}
            for fi in found][:50]
        locks, edges = set(), set()
        states = set()
        for d in dumps:
            graph = d.get("lock_graph") or {}
            locks.update(lk["name"] for lk in graph.get("locks", ()))
            edges.update((e["from"], e["to"])
                         for e in graph.get("edges", ()))
            states.update(d.get("tracked_shared_states", ()))
        out["lock_graph"] = {"locks": len(locks), "edges": len(edges)}
        out["tracked_shared_states"] = len(states)
    except Exception as exc:
        out["runtime_findings"] = None
        out["dump_error"] = repr(exc)
    lint_cmd = [sys.executable, os.path.join(REPO, "tools", "mxlint.py"),
                "--tsan-report", "--json",
                os.path.join(REPO, "incubator_mxnet_tpu"), log]
    try:
        lint = subprocess.run(lint_cmd, cwd=REPO, capture_output=True,
                              text=True, timeout=600)
        summary = json.loads(lint.stdout)
        out["lint_findings"] = summary["lint_findings"]
        out["scanned"] = summary["scanned"]
    except Exception as exc:
        out["lint_findings"] = None
        out["lint_error"] = repr(exc)
    out["clean"] = (out.get("rc") == 0
                    and out.get("runtime_findings") == 0
                    and out.get("lint_findings") == 0)
    return out


def io_stage():
    """Data-plane stage: run tools/run_io_bench.py --quick in a
    throwaway process — h2d probe (memcpy / blocking / pipelined ring),
    real-vs-synthetic training lanes on the uint8-wire convnet, the
    zero-steady-recompile check, and the MXNET_TSAN=1 ring sweep — and
    attach its BENCH_IO.json gates to the round.  The input pipeline's
    "real data trains as fast as synthetic" claim becomes checkable
    evidence next to the parity outcomes."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_io_bench.py"),
           "--quick", "--json",
           "--out", os.path.join(REPO, "BENCH_IO.json")]
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=1800,
                             env=dict(os.environ, JAX_PLATFORMS="cpu"))
        summary = json.loads(out.stdout)
        summary["rc"] = out.returncode
        summary.get("tsan", {}).pop("detail", None)
        return summary
    except Exception as exc:
        return {"error": f"io stage failed: {exc!r}"}


def obs_stage():
    """Telemetry-plane stage: run tools/run_obs_gate.py --quick in a
    throwaway process — a traced mini fused fit plus a serving burst
    with a mid-flight replica kill, merged by mxtrace — and attach its
    OBS_REPORT.json artifact to the round.  Gates: zero orphan spans
    in the merged cross-process trace, tracing+metrics overhead < 2%
    on the fused-step and serving hot paths (calibrated per-span cost
    x measured span rate), and scrape output that parses as valid
    Prometheus text with the core namespaces present.  Observability
    claims become checkable evidence next to the parity outcomes."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_obs_gate.py"),
           "--quick", "--json",
           "--out", os.path.join(REPO, "OBS_REPORT.json")]
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=1800,
                             env=dict(os.environ, JAX_PLATFORMS="cpu"))
        summary = json.loads(out.stdout)
        summary["rc"] = out.returncode
        summary.get("trace", {}).pop("orphans", None)
        return summary
    except Exception as exc:
        return {"error": f"obs stage failed: {exc!r}"}


def scaling_stage():
    """Scaling-curve stage: run tools/run_scaling.py --quick in a
    throwaway process — the dp=1/2/4/8 sweep over host-platform virtual
    devices through the public `Module.fit` path plus the comm-heavy
    bucketed-vs-single-bucket A/B — and attach its BENCH_SCALING
    artifact (per-point throughput + weak-scaling efficiency + kvstore
    communication economy, gates: dp=8 efficiency, bucketed speedup,
    zero steady-state recompiles, dispatches O(buckets)) to the round.
    Pod-scale throughput claims become checkable evidence next to the
    parity outcomes."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_scaling.py"),
           "--quick", "--json", "--out",
           os.path.join(REPO, "BENCH_SCALING.json")]
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=3600)
        summary = json.loads(out.stdout)
        summary["rc"] = out.returncode
        return summary
    except Exception as exc:
        return {"error": f"scaling stage failed: {exc!r}"}


def llm_stage():
    """Transformer-LM serving stage: run tools/run_lm_bench.py --quick
    in a throwaway process — one mixed-length trace decoded lockstep
    (static batching) and through the continuous-batching
    `DecodeEngine` on the SAME warm programs — and attach its BENCH_LM
    artifact (gates: continuous >= 2x static aggregate tokens/s, zero
    steady-state recompiles, interactive p99 inside the degradation
    SLO under a batch flood) to the round.  The flagship-model serving
    claims become checkable evidence next to the parity outcomes."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_lm_bench.py"),
           "--quick", "--json",
           "--out", os.path.join(REPO, "BENCH_LM.json")]
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=1800,
                             env=dict(os.environ, JAX_PLATFORMS="cpu"))
        summary = json.loads(out.stdout)
        summary["rc"] = out.returncode
        return summary
    except Exception as exc:
        return {"error": f"llm stage failed: {exc!r}"}


def chaos_decode_stage():
    """Continuous-batching chaos stage: run tools/run_chaos.py --decode
    in a throwaway process — steady-state mixed-ladder traffic (zero
    compiles, zero recompile findings) and one `DecodeReplica`
    SIGKILLed mid-decode (zero admitted sequences lost, zero duplicate
    deliveries, replay on the survivor) — and attach its CHAOS_DECODE
    artifact to the round."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_chaos.py"),
           "--decode", "--json", "--out", ""]
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=1800)
        summary = json.loads(out.stdout)
        summary["rc"] = out.returncode
        return summary
    except Exception as exc:
        return {"error": f"chaos decode stage failed: {exc!r}"}


def chaos_embed_stage():
    """Sharded-embedding chaos stage: run tools/run_chaos.py --embedding
    in a throwaway process — an embedding row-shard server SIGKILLed
    mid-traffic, once during Module.fit training (structured
    ServerLostError naming the shard + rows; resume from the table
    checkpoint bit-identical to a clean reference) and once under
    router serving load (on_shard_lost respawn + replace_shard, zero
    lost admitted requests) — and attach its CHAOS_EMBED artifact."""
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_chaos.py"),
           "--embedding", "--json", "--out", ""]
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=1800)
        summary = json.loads(out.stdout)
        summary["rc"] = out.returncode
        return summary
    except Exception as exc:
        return {"error": f"chaos embedding stage failed: {exc!r}"}


def loop_stage():
    """Continuous train-to-serve loop stage, two halves:

    * ``run_chaos.py --loop`` — a REAL trainer process whose shard is
      corrupted mid-loop: the fleet must never serve the poisoned
      model (guardian rollback → registry fence → canary gate), zero
      admitted requests lost, next clean version within the freshness
      SLO (CHAOS_LOOP artifact);
    * ``run_loop_gate.py`` — one clean in-process loop gating the
      sunny-day invariants: >=3 canary promotions while training runs,
      zero rejections, zero lost requests, zero post-warmup XLA
      programs across every swap, ``loop.freshness_lag_s`` within SLO
      and visible in the obs scrape plane (LOOP_REPORT artifact)."""
    out = {}
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_chaos.py"),
           "--loop", "--json", "--out", ""]
    try:
        r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                           timeout=1800)
        chaos = json.loads(r.stdout)
        chaos["rc"] = r.returncode
        out["chaos"] = chaos
    except Exception as exc:
        out["chaos"] = {"error": f"chaos loop stage failed: {exc!r}"}
    cmd = [sys.executable, os.path.join(REPO, "tools", "run_loop_gate.py"),
           "--out", os.path.join(REPO, "LOOP_REPORT.json")]
    try:
        r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                           timeout=900)
        with open(os.path.join(REPO, "LOOP_REPORT.json")) as f:
            gate = json.load(f)
        out["gate"] = {"rc": r.returncode,
                       "all_passed": gate.get("all_passed"),
                       "gates": gate.get("gates"),
                       "promotions": gate.get("promotions"),
                       "max_freshness_lag_s":
                           gate.get("max_freshness_lag_s")}
    except Exception as exc:
        out["gate"] = {"error": f"loop gate failed: {exc!r}"}
    out["all_passed"] = bool(
        out.get("chaos", {}).get("all_passed")
        and out.get("gate", {}).get("all_passed"))
    return out


def coldstart_stage():
    """Cold-start stage: the warmup CLI's built-in probe, run cold then
    warm in fresh subprocesses (tools/warmup.py coldstart_probe) — the
    second process must load every executable from the disk tier (zero
    compiles).  The artifact records cold vs warm compile_s and the
    warm/cold ratio, so program-cache regressions (a key that stops
    matching across processes, a serialization break) become checkable
    evidence next to the parity outcomes.

    A second subprocess runs ``warmup.py --measure-budgets`` against
    COST_BUDGETS.json's 'measured' section: per-program compile_s and
    peak_hbm_mb, plus the fused-step-vs-pure-JAX compile ratio (<=1.5x
    cap).  A missing required entry or a regression past tolerance
    fails the stage (``budget_gate_ok`` false, rc nonzero)."""
    out = {}
    try:
        sys.path.insert(0, os.path.join(REPO, "tools"))
        from warmup import coldstart_probe
        out = coldstart_probe()
    except Exception as exc:
        out = {"error": f"coldstart stage failed: {exc!r}"}
    cmd = [sys.executable, os.path.join(REPO, "tools", "warmup.py"),
           "--measure-budgets", "--budgets",
           os.path.join(REPO, "COST_BUDGETS.json"), "--json"]
    try:
        r = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                           timeout=900)
        gate = json.loads(r.stdout.strip().splitlines()[-1])
        out["budgets"] = {
            "rc": gate.get("rc"),
            "missing": gate.get("missing"),
            "measured": gate.get("measured"),
            "findings": [f for f in gate.get("findings", ())
                         if f.get("severity") != "hint"],
        }
        out["budget_gate_ok"] = gate.get("rc") == 0
    except Exception as exc:
        out["budgets"] = {"error": f"budget gate failed: {exc!r}"}
        out["budget_gate_ok"] = False
    return out


def main():
    rnd = "%02d" % (int(sys.argv[1]) if len(sys.argv) > 1 else next_round())
    t0 = time.time()
    cmd = [sys.executable, "-m", "pytest", "tests_tpu", "-q", "-rA",
           "--tb=line", "-p", "no:cacheprovider"]
    proc = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                          timeout=3000)
    output = proc.stdout + proc.stderr
    counts, tests = parse_outcomes(output)
    artifact = {
        "round": rnd,
        "rc": proc.returncode,
        **counts,
        "duration_s": round(time.time() - t0, 1),
        "git_rev": git_revision(),
        "jax": probe_backend(),
        "mxlint": mxlint_stage(),
        "cost": cost_stage(),
        "sharding": sharding_stage(),
        "serving": serving_stage(),
        "chaos": chaos_stage(),
        "chaos_pod": chaos_pod_stage(),
        "chaos_serving": chaos_serving_stage(),
        "chaos_fleet": chaos_fleet_stage(),
        "chaos_train": chaos_train_stage(),
        "chaos_decode": chaos_decode_stage(),
        "chaos_embed": chaos_embed_stage(),
        "loop": loop_stage(),
        "llm": llm_stage(),
        "coldstart": coldstart_stage(),
        "scaling": scaling_stage(),
        "tsan": tsan_stage(),
        "obs": obs_stage(),
        "io": io_stage(),
        "cmd": " ".join(cmd[2:]),
        "tests": tests[:500],
        "tail": "\n".join(output.strip().splitlines()[-12:])[-2000:],
    }
    path = os.path.join(REPO, f"TPU_PARITY_r{rnd}.json")
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps({k: v for k, v in artifact.items()
                      if k not in ("tail", "tests")}))
    print("artifact:", path)
    return 0 if proc.returncode == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
