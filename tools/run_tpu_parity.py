#!/usr/bin/env python
"""Run the on-chip registry parity battery (tests_tpu/) and emit a
driver-visible artifact `TPU_PARITY_r<N>.json` with pass/fail/skip counts
(reference pattern: `tests/python/gpu/test_operator_gpu.py` re-running the
CPU suite on the device).

Usage: python tools/run_tpu_parity.py [round_number]
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    rnd = sys.argv[1] if len(sys.argv) > 1 else "04"
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests_tpu", "-q", "--tb=line",
         "-p", "no:cacheprovider"],
        cwd=REPO, capture_output=True, text=True, timeout=3000)
    out = proc.stdout + proc.stderr
    counts = {"passed": 0, "failed": 0, "skipped": 0, "errors": 0}
    for key in counts:
        m = re.search(rf"(\d+) {key[:-1] if key != 'errors' else 'error'}",
                      out)
        if m:
            counts[key] = int(m.group(1))
    tail = "\n".join(out.strip().splitlines()[-12:])
    artifact = {
        "round": rnd,
        "rc": proc.returncode,
        **counts,
        "duration_s": round(time.time() - t0, 1),
        "cmd": "python -m pytest tests_tpu -q",
        "tail": tail[-2000:],
    }
    path = os.path.join(REPO, f"TPU_PARITY_r{rnd}.json")
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps({k: v for k, v in artifact.items() if k != "tail"}))
    return 0 if proc.returncode == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
