#!/usr/bin/env python
"""AOT warmup CLI for the unified program cache (compile/ subsystem).

Compile a model's program set ahead of traffic and persist the XLA
executables into the on-disk program cache, so the NEXT process — a
serving replica, a resumed training job, a c_predict embedder — loads
compiled programs instead of paying the 28–105 s cold-start compile.

Usage:

  # warm one model's bucket ladder into a cache dir
  python tools/warmup.py --cache-dir /var/cache/mxnet-programs \\
      --symbol model-symbol.json --params model-0000.params \\
      --data-shape data:1,3,224,224 --buckets 1,2,4,8,16,32

  # drive a whole manifest (several models + program payload dirs)
  python tools/warmup.py --cache-dir DIR --manifest warmup.json

  # write the manifest for later instead of (only) warming now
  python tools/warmup.py ... --emit-manifest warmup.json

  # built-in cold-start probe (run twice: cold then warm)
  python tools/warmup.py --cache-dir DIR --selftest --json

Parameters are optional: the compiled program depends on shapes only,
so zeros at the inferred parameter shapes produce the identical
executable production weights will load.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def coldstart_probe(timeout=600):
    """Run the built-in warmup selftest TWICE in fresh subprocesses
    against a throwaway cache dir: the first pays the XLA compiles, the
    second must load every executable from the disk tier.  Returns
    {cold_compile_s, warm_compile_s, *_compiles, *_disk_hits,
    warm_cold_ratio, zero_compile_warm_start} or {"error": ...}.

    Shared by bench.py's coldstart lane and run_tpu_parity.py's
    coldstart stage.  Each phase is its OWN process, so the caller must
    not be holding an exclusively-locked accelerator (on TPU, run this
    before the parent initializes jax — libtpu locks the chip)."""
    import json as _json
    import shutil
    import subprocess
    import tempfile
    cache = tempfile.mkdtemp(prefix="mxnet-coldstart-")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, os.path.abspath(__file__),
           "--cache-dir", cache, "--selftest", "--json"]
    out = {}
    try:
        for phase in ("cold", "warm"):
            r = subprocess.run(cmd, cwd=repo, capture_output=True,
                               text=True, timeout=timeout)
            if r.returncode != 0:
                return {"error": "%s warmup rc=%d" % (phase, r.returncode),
                        "tail": r.stderr.strip()[-500:]}
            d = _json.loads(r.stdout.strip().splitlines()[-1])
            out[phase + "_compile_s"] = d["compile_s"]
            out[phase + "_compiles"] = d["compiles"]
            out[phase + "_disk_hits"] = d["disk_hits"]
        if out["cold_compile_s"]:
            out["warm_cold_ratio"] = round(
                out["warm_compile_s"] / out["cold_compile_s"], 3)
        out["zero_compile_warm_start"] = out["warm_compiles"] == 0 and \
            out["warm_disk_hits"] > 0
        return out
    except Exception as exc:
        return {"error": f"coldstart probe failed: {exc!r}"}
    finally:
        shutil.rmtree(cache, ignore_errors=True)


def _parse_shape(spec):
    name, _, dims = spec.partition(":")
    if not dims:
        raise SystemExit(f"--data-shape {spec!r}: expected name:d0,d1,...")
    return [name, [int(d) for d in dims.split(",")]]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cache-dir", required=True,
                    help="program cache directory (the disk tier; also "
                         "settable via MXNET_PROGRAM_CACHE_DIR)")
    ap.add_argument("--manifest", help="warmup manifest JSON to drive")
    ap.add_argument("--symbol", help="model symbol JSON file")
    ap.add_argument("--params", help="model .params file (optional: "
                                     "zeros at inferred shapes otherwise)")
    ap.add_argument("--data-shape", action="append", default=[],
                    metavar="name:d0,d1,...",
                    help="request input shape (repeatable); d0 is the "
                         "batch axis the buckets replace")
    ap.add_argument("--buckets", default="1,2,4,8,16,32",
                    help="batch-size ladder to compile")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--name", default="model")
    ap.add_argument("--emit-manifest", metavar="PATH",
                    help="also write the equivalent manifest JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="warm the built-in probe model (cold/warm "
                         "compile-time measurement)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the summary as one JSON line")
    args = ap.parse_args(argv)

    from incubator_mxnet_tpu import compile as mxc

    if args.selftest:
        summary = mxc.warmup.selftest(args.cache_dir)
    elif args.manifest:
        summary = mxc.warm(args.manifest, cache_dir=args.cache_dir)
    else:
        if not (args.symbol and args.data_shape):
            ap.error("need --manifest, --selftest, or --symbol with "
                     "--data-shape")
        manifest = {
            "version": mxc.warmup.MANIFEST_VERSION,
            "models": [{
                "name": args.name,
                "symbol": os.path.abspath(args.symbol),
                "params": os.path.abspath(args.params) if args.params
                else None,
                "data_shapes": [_parse_shape(s) for s in args.data_shape],
                "buckets": [int(b) for b in args.buckets.split(",")],
                "dtype": args.dtype,
            }],
        }
        if args.emit_manifest:
            mxc.write_manifest(args.emit_manifest, manifest["models"])
        summary = mxc.warm(manifest, cache_dir=args.cache_dir)

    if args.as_json:
        print(json.dumps(summary))
    else:
        print("warmed: %d compiles, %d disk hits, %.2fs"
              % (summary.get("compiles", 0), summary.get("disk_hits", 0),
                 summary.get("compile_s", 0.0)))
        for m in summary.get("models", []):
            print("  %(name)s buckets=%(buckets)s compiles=%(compiles)d "
                  "disk_hits=%(disk_hits)d %(compile_s).2fs" % m)
    return 0


if __name__ == "__main__":
    sys.exit(main())
