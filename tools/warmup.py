#!/usr/bin/env python
"""AOT warmup CLI for the unified program cache (compile/ subsystem).

Compile a model's program set ahead of traffic and persist the XLA
executables into the on-disk program cache, so the NEXT process — a
serving replica, a resumed training job, a c_predict embedder — loads
compiled programs instead of paying the 28–105 s cold-start compile.

Usage:

  # warm one model's bucket ladder into a cache dir
  python tools/warmup.py --cache-dir /var/cache/mxnet-programs \\
      --symbol model-symbol.json --params model-0000.params \\
      --data-shape data:1,3,224,224 --buckets 1,2,4,8,16,32

  # drive a whole manifest (several models + program payload dirs)
  python tools/warmup.py --cache-dir DIR --manifest warmup.json

  # write the manifest for later instead of (only) warming now
  python tools/warmup.py ... --emit-manifest warmup.json

  # built-in cold-start probe (run twice: cold then warm)
  python tools/warmup.py --cache-dir DIR --selftest --json

Parameters are optional: the compiled program depends on shapes only,
so zeros at the inferred parameter shapes produce the identical
executable production weights will load.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def coldstart_probe(timeout=600):
    """Run the built-in warmup selftest TWICE in fresh subprocesses
    against a throwaway cache dir: the first pays the XLA compiles, the
    second must load every executable from the disk tier.  Returns
    {cold_compile_s, warm_compile_s, *_compiles, *_disk_hits,
    warm_cold_ratio, zero_compile_warm_start} or {"error": ...}.

    Shared by bench.py's coldstart lane and run_tpu_parity.py's
    coldstart stage.  Each phase is its OWN process, so the caller must
    not be holding an exclusively-locked accelerator (on TPU, run this
    before the parent initializes jax — libtpu locks the chip)."""
    import json as _json
    import shutil
    import subprocess
    import tempfile
    cache = tempfile.mkdtemp(prefix="mxnet-coldstart-")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, os.path.abspath(__file__),
           "--cache-dir", cache, "--selftest", "--json"]
    out = {}
    try:
        for phase in ("cold", "warm"):
            r = subprocess.run(cmd, cwd=repo, capture_output=True,
                               text=True, timeout=timeout)
            if r.returncode != 0:
                return {"error": "%s warmup rc=%d" % (phase, r.returncode),
                        "tail": r.stderr.strip()[-500:]}
            d = _json.loads(r.stdout.strip().splitlines()[-1])
            out[phase + "_compile_s"] = d["compile_s"]
            out[phase + "_compiles"] = d["compiles"]
            out[phase + "_disk_hits"] = d["disk_hits"]
        if out["cold_compile_s"]:
            out["warm_cold_ratio"] = round(
                out["warm_compile_s"] / out["cold_compile_s"], 3)
        out["zero_compile_warm_start"] = out["warm_compiles"] == 0 and \
            out["warm_disk_hits"] > 0
        return out
    except Exception as exc:
        return {"error": f"coldstart probe failed: {exc!r}"}
    finally:
        shutil.rmtree(cache, ignore_errors=True)


def _fused_vs_jax_compile():
    """Cold-compile the FULL fused train step of a tiny convnet through
    the public Module path, and a hand-written pure-JAX train step of
    the same math (conv3x3/8 + relu + fc10 + softmax-CE + momentum SGD
    + accuracy), both phase-timed.  The ratio is the coldstart budget
    gate: the framework's one-program step must compile within 1.5x of
    what the same model costs in raw JAX."""
    import time as _time

    import numpy as np
    import jax
    import jax.numpy as jnp

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import io, sym

    data = sym.Variable("data")
    x = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        name="conv0")
    x = sym.Activation(x, act_type="relu", name="relu0")
    x = sym.Flatten(x, name="flatten0")
    x = sym.FullyConnected(x, num_hidden=10, name="fc0")
    net = sym.SoftmaxOutput(x, name="softmax")

    rng = np.random.RandomState(0)
    X = rng.randn(32, 3, 8, 8).astype("f4")
    y = rng.randint(0, 10, 32).astype("f4")
    it = io.NDArrayIter(X, y, batch_size=16, label_name="softmax_label")
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    mod = mx.mod.Module(net, context=ctx)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore="device", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    metric = mx.metric.create("acc")
    for b in list(it)[:2]:
        mod.fit_step(b, metric)
    fused = mod._fused_step
    if fused is None or fused.broken:
        raise RuntimeError("fused train step did not engage")
    ph = fused.compile_phase_stats()
    fused_s = (ph["trace_s"] or 0.0) + sum(
        p["lower_s"] + p["compile_s"] for p in ph["programs"])

    # the pure-JAX control: same forward/loss/backward/update/metric
    def loss_fn(w, img, lab):
        z = jax.lax.conv_general_dilated(
            img, w["cw"], (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        z = z + w["cb"][None, :, None, None]
        z = jnp.maximum(z, 0.0).reshape(img.shape[0], -1)
        z = z @ w["fw"].T + w["fb"]
        z = z - jax.scipy.special.logsumexp(z, axis=1, keepdims=True)
        hot = jax.nn.one_hot(lab.astype("int32"), 10)
        return -jnp.mean(jnp.sum(hot * z, axis=1)), z

    def train_step(w, m, img, lab, lr):
        (loss, z), g = jax.value_and_grad(loss_fn, has_aux=True)(
            w, img, lab)
        new_m = jax.tree_util.tree_map(lambda mi, gi: 0.9 * mi + gi, m, g)
        new_w = jax.tree_util.tree_map(lambda wi, mi: wi - lr * mi,
                                       w, new_m)
        acc = jnp.mean((jnp.argmax(z, 1) ==
                        lab.astype("int32")).astype("f4"))
        return new_w, new_m, loss, acc

    w = {"cw": jnp.zeros((8, 3, 3, 3), "f4"),
         "cb": jnp.zeros((8,), "f4"),
         "fw": jnp.zeros((10, 8 * 8 * 8), "f4"),
         "fb": jnp.zeros((10,), "f4")}
    m = jax.tree_util.tree_map(jnp.zeros_like, w)
    img = jnp.zeros((16, 3, 8, 8), "f4")
    lab = jnp.zeros((16,), "f4")
    jfn = jax.jit(train_step)
    t0 = _time.perf_counter()
    lowered = jfn.lower(w, m, img, lab, 0.1)
    t1 = _time.perf_counter()
    lowered.compile()
    t2 = _time.perf_counter()
    jax_s = t2 - t0
    return {
        "compile_s": round(fused_s, 4),
        "trace_s": round(ph["trace_s"] or 0.0, 4),
        "jaxpr_eqns": ph["jaxpr_eqns"],
        "jax_control_compile_s": round(jax_s, 4),
        "jax_control_lower_s": round(t1 - t0, 4),
        "compile_ratio_vs_jax": round(fused_s / jax_s, 3) if jax_s else
        None,
    }


def measure_coldstart_budgets():
    """Measured cold-start numbers for the budget gate, per bench
    program (`analysis.cost.bench_programs`):

    * ``compile_s`` — jit ``lower``+``compile`` wall seconds of the
      program's inference graph;
    * ``peak_hbm_mb`` — the compiled executable's own XLA memory
      analysis (temp + argument + output buffers) on an accelerator
      backend; on CPU hosts, where the runtime does not report device
      memory, the mxcost liveness prediction stands in
      (``peak_hbm_source`` records which);
    * ``predicted_peak_hbm_mb`` — the mxcost static liveness peak, so
      the committed baseline pins measurement to prediction: a TPU run
      whose measured peak drifts past the 15% tolerance around the
      committed (predicted) entry fails the gate;

    plus ``fused.convnet_step`` — the full fused train step against a
    hand-written pure-JAX control of the same model
    (``compile_ratio_vs_jax``, gated at <=1.5x).

    Returns {program: {metric: value}} ready for
    `analysis.budgets.check_measured` / `snapshot_measured`.
    """
    import time as _time

    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu.analysis import cost as _cost
    from incubator_mxnet_tpu.symbol.symbol import graph_eval_fn

    backend = jax.default_backend()
    out = {}
    for name, (sym, shapes, dtypes) in \
            sorted(_cost.bench_programs().items()):
        prog = _cost.analyze_symbol(sym, shapes=shapes, dtypes=dtypes,
                                    target=name)
        predicted_mb = (prog.peak_hbm_bytes or 0) / float(1 << 20)
        arg_shapes, _, aux_shapes = sym.infer_shape(**shapes)
        shape_by = dict(zip(sym.list_arguments(), arg_shapes))
        aux_by = dict(zip(sym.list_auxiliary_states(), aux_shapes))
        dt = dtypes or {}
        fn, arg_nodes, aux_nodes, _n_rng = graph_eval_fn(sym, False)
        args = [jnp.zeros(shape_by[n.name], dt.get(n.name, "float32"))
                for n in arg_nodes]
        auxs = [jnp.zeros(aux_by[n.name], dt.get(n.name, "float32"))
                for n in aux_nodes]
        key = jax.random.PRNGKey(0)
        jfn = jax.jit(fn)
        t0 = _time.perf_counter()
        lowered = jfn.lower(args, auxs, key)
        t1 = _time.perf_counter()
        compiled = lowered.compile()
        t2 = _time.perf_counter()
        measured_mb = None
        if backend != "cpu":
            try:
                ma = compiled.memory_analysis()
                measured_mb = (ma.temp_size_in_bytes +
                               ma.argument_size_in_bytes +
                               ma.output_size_in_bytes) / float(1 << 20)
            except Exception:
                measured_mb = None
        out[name] = {
            "compile_s": round(t2 - t0, 4),
            "lower_s": round(t1 - t0, 4),
            "peak_hbm_mb": round(measured_mb if measured_mb is not None
                                 else predicted_mb, 4),
            "peak_hbm_source": "measured" if measured_mb is not None
            else "estimated",
            "predicted_peak_hbm_mb": round(predicted_mb, 4),
        }
    try:
        out["fused.convnet_step"] = _fused_vs_jax_compile()
    except Exception as exc:
        out["fused.convnet_step"] = {"error": repr(exc)[:200]}
    return out


# the measured programs the coldstart budget gate REQUIRES baselined
# entries for (run_tpu_parity's coldstart stage fails when one is
# missing from COST_BUDGETS.json's "measured" section)
REQUIRED_MEASURED = ("quantization.convnet_fp32",
                     "quantization.convnet_bf16",
                     "quantization.convnet_int8",
                     "fused.convnet_step")


def measured_budget_gate(budgets_path, write=False):
    """Measure, then gate against (or re-baseline into) the budget
    file's 'measured' section.  Returns a JSON-able summary with
    ``rc`` 0/1: regression or a missing required entry fails."""
    from incubator_mxnet_tpu.analysis import budgets as _budgets

    measured = measure_coldstart_budgets()
    summary = {"measured": measured}
    gated = {k: v for k, v in measured.items() if "error" not in v}
    budgets = _budgets.load(budgets_path)
    if write:
        _budgets.snapshot_measured(gated, budgets)
        _budgets.save(budgets_path, budgets)
        summary["wrote"] = budgets_path
        summary["rc"] = 0
        return summary
    report, deltas = _budgets.check_measured(gated, budgets)
    from incubator_mxnet_tpu.analysis.findings import ERROR
    findings = [f.as_dict() for f in report]
    missing = [name for name in REQUIRED_MEASURED
               if name not in (budgets.get("measured") or {})]
    errors = [f for f in report if f.severity == ERROR]
    summary.update(deltas=deltas, findings=findings, missing=missing,
                   rc=1 if errors or missing else 0)
    return summary


def _parse_shape(spec):
    name, _, dims = spec.partition(":")
    if not dims:
        raise SystemExit(f"--data-shape {spec!r}: expected name:d0,d1,...")
    return [name, [int(d) for d in dims.split(",")]]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cache-dir",
                    help="program cache directory (the disk tier; also "
                         "settable via MXNET_PROGRAM_CACHE_DIR); required "
                         "for every mode except --measure-budgets")
    ap.add_argument("--manifest", help="warmup manifest JSON to drive")
    ap.add_argument("--symbol", help="model symbol JSON file")
    ap.add_argument("--params", help="model .params file (optional: "
                                     "zeros at inferred shapes otherwise)")
    ap.add_argument("--data-shape", action="append", default=[],
                    metavar="name:d0,d1,...",
                    help="request input shape (repeatable); d0 is the "
                         "batch axis the buckets replace")
    ap.add_argument("--buckets", default="1,2,4,8,16,32",
                    help="batch-size ladder to compile")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--name", default="model")
    ap.add_argument("--emit-manifest", metavar="PATH",
                    help="also write the equivalent manifest JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="warm the built-in probe model (cold/warm "
                         "compile-time measurement)")
    ap.add_argument("--measure-budgets", action="store_true",
                    help="measure per-program coldstart compile_s / "
                         "peak_hbm_mb and gate them against the "
                         "'measured' section of --budgets")
    ap.add_argument("--budgets", metavar="PATH",
                    help="COST_BUDGETS.json to gate --measure-budgets "
                         "against")
    ap.add_argument("--write-budgets", action="store_true",
                    help="re-baseline the measured section instead of "
                         "gating (commit the diff)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the summary as one JSON line")
    args = ap.parse_args(argv)

    if args.measure_budgets:
        if args.budgets:
            summary = measured_budget_gate(args.budgets,
                                           write=args.write_budgets)
        else:
            summary = {"measured": measure_coldstart_budgets(), "rc": 0}
        if args.as_json:
            print(json.dumps(summary))
        else:
            for name, m in sorted(summary["measured"].items()):
                print("  %s: %s" % (name, json.dumps(m)))
            for f in summary.get("findings", ()):
                print("  %(severity)s %(code)s %(message)s" % f)
        return summary.get("rc", 0)

    if not args.cache_dir:
        ap.error("--cache-dir is required (except with --measure-budgets)")

    from incubator_mxnet_tpu import compile as mxc

    if args.selftest:
        summary = mxc.warmup.selftest(args.cache_dir)
    elif args.manifest:
        summary = mxc.warm(args.manifest, cache_dir=args.cache_dir)
    else:
        if not (args.symbol and args.data_shape):
            ap.error("need --manifest, --selftest, or --symbol with "
                     "--data-shape")
        manifest = {
            "version": mxc.warmup.MANIFEST_VERSION,
            "models": [{
                "name": args.name,
                "symbol": os.path.abspath(args.symbol),
                "params": os.path.abspath(args.params) if args.params
                else None,
                "data_shapes": [_parse_shape(s) for s in args.data_shape],
                "buckets": [int(b) for b in args.buckets.split(",")],
                "dtype": args.dtype,
            }],
        }
        if args.emit_manifest:
            mxc.write_manifest(args.emit_manifest, manifest["models"])
        summary = mxc.warm(manifest, cache_dir=args.cache_dir)

    if args.as_json:
        print(json.dumps(summary))
    else:
        print("warmed: %d compiles, %d disk hits, %.2fs"
              % (summary.get("compiles", 0), summary.get("disk_hits", 0),
                 summary.get("compile_s", 0.0)))
        for m in summary.get("models", []):
            print("  %(name)s buckets=%(buckets)s compiles=%(compiles)d "
                  "disk_hits=%(disk_hits)d %(compile_s).2fs" % m)
    return 0


if __name__ == "__main__":
    sys.exit(main())
