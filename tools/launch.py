#!/usr/bin/env python
"""Localhost/multi-node job launcher.

CLI-compatible subset of the reference launcher (`tools/launch.py:71`):

    python tools/launch.py -n 4 [-s 1] [--launcher local] python train.py ...

Spawns the parameter server and N worker processes with the dmlc tracker
env (DMLC_ROLE/DMLC_PS_ROOT_URI/DMLC_PS_ROOT_PORT/DMLC_NUM_WORKER/
DMLC_NUM_SERVER/DMLC_RANK) set, waits for the workers, then tears the
server down.  Only the `local` launcher is implemented — `ssh`/`mpi`/
`yarn`/`sge` cluster modes are out of scope for a single-image build; the
env contract is identical, so any external tracker that sets these
variables works unchanged.
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Launch a distributed job (reference tools/launch.py)")
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-s", "--num-servers", type=int, default=1,
                        help="parameter servers; keys range-shard over "
                             "them (MXNET_KVSTORE_BIGARRAY_BOUND)")
    parser.add_argument("--launcher", default="local",
                        choices=["local"],
                        help="cluster launchers: set the DMLC_* env with "
                             "your own tracker instead")
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")

    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pypath = repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")
    base_env = dict(os.environ,
                    PYTHONPATH=pypath.rstrip(os.pathsep),
                    DMLC_PS_ROOT_URI="127.0.0.1",
                    DMLC_PS_ROOT_PORT=str(port),
                    DMLC_NUM_WORKER=str(args.num_workers),
                    DMLC_NUM_SERVER=str(args.num_servers))

    servers = [subprocess.Popen(
        [sys.executable, "-m", "incubator_mxnet_tpu.dist.server"],
        env=dict(base_env, DMLC_ROLE="server", DMLC_SERVER_ID=str(i)))
        for i in range(args.num_servers)]

    workers = []
    for rank in range(args.num_workers):
        workers.append(subprocess.Popen(
            args.command,
            env=dict(base_env, DMLC_ROLE="worker", DMLC_RANK=str(rank))))

    rc = 0
    for w in workers:
        rc = w.wait() or rc
    for server in servers:
        try:
            # a clean run ends when every worker has sent its stop command;
            # on worker failure a server never hears them all — time out
            server.wait(timeout=15 if rc else 60)
        except subprocess.TimeoutExpired:
            server.terminate()
    return rc


if __name__ == "__main__":
    sys.exit(main())
