#!/usr/bin/env python
"""Continuous-batching LM serving bench: the decode engine's economics
as one JSON artifact (``BENCH_LM.json``).

Static batching decodes a batch in LOCKSTEP: every slot steps until the
longest sequence finishes, so a mixed-length trace leaves finished
slots idle-stepping — aggregate useful-tokens/s collapses to the
longest request's pace.  The `serving.DecodeEngine` evicts finished
sequences between ticks and re-admits from the queue (bucketed
prefill), so the SAME fixed-shape decode-step program stays full of
useful work.  This bench runs one mixed-length trace through both
disciplines — the same `llm.decode_core` programs, the same slot
count — and gates on the ratio.

Lanes and gates:

* **static** — lockstep batches over the trace (useful tokens / wall
  time; finished slots burn ticks until the batch's longest finishes);
* **continuous** — the same trace through `DecodeEngine` (admission,
  eviction, bucketed prefill all inside the measured window);
  gate: ``continuous >= 2x static`` aggregate tokens/s;
* **zero steady-state recompiles** — both lanes run entirely on the
  warmup-compiled ladder (one prefill per bucket + ONE decode step);
  gate: compile-count delta 0 and no `analysis.recompile` findings;
* **interactive SLO** — short interactive requests submitted while a
  batch-priority flood saturates the queue; the priority ladder must
  keep their p99 inside a band derived from the unloaded baseline
  (degradation bound, not an absolute number — CI machines vary).

Usage: python tools/run_lm_bench.py [--quick] [--json] [--out PATH]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BUCKETS = (8,)
SLOTS = 8
SHORT_NEW, LONG_NEW = 3, 40


def _cfg():
    from incubator_mxnet_tpu.llm import LMConfig
    # eos outside the vocab: random-weight argmax chains never emit it,
    # so every sequence generates exactly its budget — the two lanes'
    # useful-token accounting is identical by construction
    return LMConfig(vocab_size=64, num_layers=2, num_heads=2, hidden=32,
                    ffn_mult=2, max_len=64, eos_id=-1)


def _params(cfg, seed=9):
    import numpy as np
    rng = np.random.default_rng(seed)
    c, f = cfg.hidden, cfg.hidden * cfg.ffn_mult
    mk = lambda *s: rng.standard_normal(s).astype(np.float32) * 0.1  # noqa: E731
    p = {"lm_embed_weight": mk(cfg.vocab_size, c),
         "lm_final_ln_gamma": np.ones((c,), np.float32),
         "lm_final_ln_beta": np.zeros((c,), np.float32)}
    for i in range(cfg.num_layers):
        pre = "lm_block%d_" % i
        p[pre + "ln1_gamma"] = np.ones((c,), np.float32)
        p[pre + "ln1_beta"] = np.zeros((c,), np.float32)
        p[pre + "qkv_weight"] = mk(3 * c, c)
        p[pre + "qkv_bias"] = np.zeros((3 * c,), np.float32)
        p[pre + "out_proj_weight"] = mk(c, c)
        p[pre + "out_proj_bias"] = np.zeros((c,), np.float32)
        p[pre + "ln2_gamma"] = np.ones((c,), np.float32)
        p[pre + "ln2_beta"] = np.zeros((c,), np.float32)
        p[pre + "fc1_weight"] = mk(f, c)
        p[pre + "fc1_bias"] = np.zeros((f,), np.float32)
        p[pre + "fc2_weight"] = mk(c, f)
        p[pre + "fc2_bias"] = np.zeros((c,), np.float32)
    return p


def _trace(n_batches, seed=17):
    """Mixed-length trace, arranged so every static batch of SLOTS
    holds exactly one long request — the production shape (a few long
    generations among many short ones) and the lockstep worst case."""
    import numpy as np
    rng = np.random.default_rng(seed)
    trace = []
    for b in range(n_batches):
        budgets = [SHORT_NEW] * (SLOTS - 1) + [LONG_NEW]
        for new in budgets:
            toks = [int(t) for t in rng.integers(1, 60,
                                                 int(rng.integers(2, 9)))]
            trace.append((toks, new))
    return trace


def _static_lane(programs, cfg, trace):
    """Lockstep batches through the SAME warm programs: prefill each
    slot, then step every slot until the batch's longest budget is
    spent.  Returns (useful_tokens, wall_s, ticks)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from incubator_mxnet_tpu import fused as _fused
    from incubator_mxnet_tpu.llm import init_kv_cache
    useful = ticks = 0
    t0 = time.monotonic()
    for at in range(0, len(trace), SLOTS):
        batch = trace[at:at + SLOTS]
        ck, cv = _fused.reown_for_donation(init_kv_cache(cfg, SLOTS))
        tokens = np.zeros((SLOTS,), np.int32)
        positions = np.zeros((SLOTS,), np.int32)
        for s, (toks, _new) in enumerate(batch):
            padded = np.zeros((1, BUCKETS[0]), np.int32)
            padded[0, :len(toks)] = toks
            ck, cv, tok, _ = programs.prefill(
                programs.params, ck, cv, jnp.asarray(padded),
                jnp.int32(s), jnp.int32(len(toks)))
            tokens[s] = int(tok)
            positions[s] = len(toks)
        # lockstep: EVERY slot steps until the longest budget is spent
        for _ in range(max(new for _, new in batch) - 1):
            ck, cv, nxt, _ = programs.step(
                programs.params, ck, cv, jnp.asarray(tokens),
                jnp.asarray(positions))
            tokens = np.asarray(nxt)
            positions += 1
            ticks += 1
        jax.block_until_ready(tokens)
        del ck, cv
        useful += sum(new for _, new in batch)
    return useful, time.monotonic() - t0, ticks


def _continuous_lane(engine, trace):
    """The same trace through the engine's admission/eviction loop."""
    from concurrent.futures import wait as _wait
    t0 = time.monotonic()
    futs = [engine.submit(toks, max_new_tokens=new, rid="lm-%d" % i,
                          priority="batch")
            for i, (toks, new) in enumerate(trace)]
    done, not_done = _wait(futs, timeout=600.0)
    wall = time.monotonic() - t0
    if not_done:
        raise RuntimeError("%d sequences never resolved" % len(not_done))
    useful = sum(len(f.result(0)["tokens"]) for f in futs)
    return useful, wall


def _interactive_lane(engine, n=20, flood=24):
    """Interactive p99 under a batch-priority flood, against an
    unloaded baseline."""
    import numpy as np

    def one(priority):
        t1 = time.monotonic()
        engine.submit([5, 6, 7], max_new_tokens=SHORT_NEW,
                      priority=priority).result(120.0)
        return (time.monotonic() - t1) * 1e3

    baseline = sorted(one("interactive") for _ in range(n))
    flood_futs = [engine.submit([1 + i % 50] * 6, max_new_tokens=LONG_NEW,
                                priority="batch") for i in range(flood)]
    loaded = sorted(one("interactive") for _ in range(n))
    for f in flood_futs:
        f.result(600.0)
    p99 = lambda xs: float(np.percentile(xs, 99))  # noqa: E731
    return {"baseline_p50_ms": round(baseline[len(baseline) // 2], 2),
            "baseline_p99_ms": round(p99(baseline), 2),
            "loaded_p50_ms": round(loaded[len(loaded) // 2], 2),
            "loaded_p99_ms": round(p99(loaded), 2)}


def run_bench(quick=False):
    from incubator_mxnet_tpu import analysis
    from incubator_mxnet_tpu.serving import DecodeEngine
    analysis.recompile.reset()
    cfg = _cfg()
    engine = DecodeEngine(cfg, _params(cfg), slots=SLOTS, buckets=BUCKETS,
                          name="lmbench", admit_per_tick=SLOTS)
    try:
        warm_compiles = engine.programs.compile_count()
        warm_programs = engine.programs.program_count()
        trace = _trace(n_batches=3 if quick else 6)

        s_tokens, s_wall, s_ticks = _static_lane(engine.programs, cfg,
                                                 trace)
        c_tokens, c_wall = _continuous_lane(engine, trace)
        inter = _interactive_lane(engine, n=10 if quick else 20,
                                  flood=12 if quick else 24)

        static_tps = s_tokens / s_wall
        cont_tps = c_tokens / c_wall
        churn = [f for f in analysis.recompile.findings()
                 if str(f.get("key", "")).startswith("decode:")]
        compile_delta = engine.programs.compile_count() - warm_compiles
        # the SLO is a degradation bound off THIS machine's unloaded
        # baseline (the fleet chaos gate's pattern): the priority
        # ladder must keep interactive tail latency within 6x of
        # unloaded even while a 40-token batch flood owns the slots
        slo_ms = max(6.0 * inter["baseline_p99_ms"], 250.0)
        stats = engine.stats()
        gates = {
            "continuous_2x_static": cont_tps >= 2.0 * static_tps,
            "zero_steady_recompiles": (compile_delta == 0 and not churn),
            "interactive_slo_held": inter["loaded_p99_ms"] <= slo_ms,
        }
        return {
            "config": cfg.to_dict(),
            "slots": SLOTS,
            "buckets": list(BUCKETS),
            "trace_sequences": len(trace),
            "static": {"useful_tokens": s_tokens,
                       "wall_s": round(s_wall, 3),
                       "lockstep_ticks": s_ticks,
                       "tokens_per_s": round(static_tps, 1)},
            "continuous": {"useful_tokens": c_tokens,
                           "wall_s": round(c_wall, 3),
                           "engine_ticks": stats["ticks"],
                           "tokens_per_s": round(cont_tps, 1)},
            "speedup": round(cont_tps / static_tps, 2),
            "interactive": dict(inter, slo_ms=round(slo_ms, 1)),
            "programs": {"warmup_compiles": warm_compiles,
                         "programs": warm_programs,
                         "post_warmup_compiles": compile_delta,
                         "recompile_findings": len(churn)},
            "gates": gates,
            "all_passed": all(gates.values()),
        }
    finally:
        engine.close(drain=False)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="run_lm_bench", description=__doc__)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--out", default=os.path.join(REPO, "BENCH_LM.json"),
                    help="artifact path ('' skips writing)")
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    artifact = run_bench(quick=args.quick)
    artifact["quick"] = args.quick
    artifact["duration_s"] = round(time.time() - t0, 1)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
    if args.as_json:
        print(json.dumps(artifact))
    else:
        print("lm_bench: static %.1f tok/s, continuous %.1f tok/s "
              "(%.2fx), interactive p99 %.1fms (slo %.1fms), "
              "post-warmup compiles %d, all_passed=%s%s" %
              (artifact["static"]["tokens_per_s"],
               artifact["continuous"]["tokens_per_s"],
               artifact["speedup"],
               artifact["interactive"]["loaded_p99_ms"],
               artifact["interactive"]["slo_ms"],
               artifact["programs"]["post_warmup_compiles"],
               artifact["all_passed"],
               (" -> " + args.out) if args.out else ""))
    return 0 if artifact["all_passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
