#!/usr/bin/env python
"""Communication micro-benchmark (reference `tools/bandwidth/measure.py`:
kvstore push/pull bandwidth over a model's weight shapes).

Measures, on whatever mesh is available (the real chip, or the virtual
8-device CPU mesh via `--cpu-mesh`):

* raw `psum` all-reduce bus bandwidth across message sizes (the
  collective data plane everything else rides), and
* end-to-end kvstore push+pull rate over ResNet-50-like weight shapes
  for each single-process kvstore type — the reference tool's number.

Prints one JSON line.  Bus bandwidth uses the standard ring-all-reduce
accounting: 2 * (n-1)/n * bytes / time.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def resnet50_shapes():
    """Representative weight shapes (conv + fc) totalling ~25M params."""
    shapes = [(64, 3, 7, 7), (1000, 2048), (1000,)]
    for cin, cmid, cout, n in [(64, 64, 256, 3), (256, 128, 512, 4),
                               (512, 256, 1024, 6), (1024, 512, 2048, 3)]:
        for _ in range(n):
            shapes += [(cmid, cin, 1, 1), (cmid, cmid, 3, 3),
                       (cout, cmid, 1, 1)]
            cin = cout
    return shapes


def measure_allreduce(sizes_mb, repeat=10):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.array(jax.devices())
    n = len(devs)
    mesh = Mesh(devs, ("x",))
    out = {}
    for mb in sizes_mb:
        nelem = int(mb * 2 ** 20 // 4)
        x = jax.device_put(
            np.ones((n, nelem), np.float32),
            NamedSharding(mesh, P("x")))

        @jax.jit
        def ar(v):
            return jax.lax.with_sharding_constraint(
                jnp.broadcast_to(v.sum(0, keepdims=True), v.shape),
                NamedSharding(mesh, P("x")))

        r = ar(x)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        for _ in range(repeat):
            r = ar(r)
        jax.block_until_ready(r)
        dt = (time.perf_counter() - t0) / repeat
        bus = 2 * (n - 1) / n * (mb / 1024) / dt   # GB/s, ring accounting
        out[f"allreduce_{mb}MB_GBps"] = round(bus, 2)
    return out, n


def measure_kvstore(kv_type, repeat=5):
    import incubator_mxnet_tpu as mx

    try:
        kv = mx.kvstore.create(kv_type)
    except Exception as e:
        return {"error": repr(e)[:120]}
    shapes = resnet50_shapes()
    rng = np.random.RandomState(0)
    vals = [mx.nd.array(rng.rand(*s).astype("f4")) for s in shapes]
    keys = list(range(len(shapes)))
    for k, v in zip(keys, vals):
        kv.init(k, v)
    outs = [mx.nd.zeros(s) for s in shapes]
    total_mb = sum(v.size for v in vals) * 4 / 2 ** 20

    def once():
        kv.push(keys, vals)
        kv.pull(keys, out=outs)
        outs[-1].asnumpy()   # sync

    once()
    t0 = time.perf_counter()
    for _ in range(repeat):
        once()
    dt = (time.perf_counter() - t0) / repeat
    return {"total_MB": round(total_mb, 1),
            "push_pull_GBps": round(total_mb / 1024 / dt, 3),
            "push_pull_ms": round(dt * 1e3, 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu-mesh", type=int, default=0,
                    help="force an N-device virtual CPU mesh (the dryrun "
                         "configuration); 0 = whatever devices exist")
    ap.add_argument("--sizes-mb", type=float, nargs="+",
                    default=[1, 16, 64])
    ap.add_argument("--kv-types", type=str, nargs="+",
                    default=["local", "device"])
    args = ap.parse_args()

    if args.cpu_mesh:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={args.cpu_mesh}")
        import jax
        jax.config.update("jax_platforms", "cpu")

    result = {"metric": "comm_bandwidth"}
    ar, n = measure_allreduce(args.sizes_mb)
    result["n_devices"] = n
    result.update(ar)
    for kvt in args.kv_types:
        r = measure_kvstore(kvt)
        result.update({f"kv_{kvt}_{k}": v for k, v in r.items()})
    print(json.dumps(result))


if __name__ == "__main__":
    main()
