#!/usr/bin/env python
"""Obs gate: the telemetry plane's CI stage (OBS_REPORT.json).

Certifies the unified telemetry plane's three contracts in one run:

1. **complete span trees** — a traced mini fused fit plus a serving
   burst over a router fleet (one replica killed mid-burst, so the
   failover path is exercised) must merge (tools/mxtrace.py) into
   trees with ZERO orphan spans: every admitted request and every
   training step reads as one connected tree;
2. **bounded overhead** — tracing+metrics enabled must cost < 2% on
   the fused-step and serving hot paths.  The gated number is the
   telemetry plane's measured SELF-TIME share of the traced run's
   wall time (span hooks + buffering + serialization + flush IO,
   summed across threads — GIL-serialized, so the sum is the honest
   tax); the off-vs-on wall delta rides along as evidence but is too
   noisy on shared CI hosts to gate at 2%;
3. **valid scrape** — the Prometheus text served by the ``metrics``
   transport frame must parse under the strict
   `obs.metrics.parse_prometheus` grammar and carry the core
   namespaces (kvstore, serving, profiler).

Usage: python tools/run_obs_gate.py [--quick] [--json]
       [--out OBS_REPORT.json]

Exit 0 only when every gate holds; the artifact is written either way
(a red run is evidence too).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

OVERHEAD_GATE = 0.02


def _make_module(batch=32, in_dim=64, hidden=64, n_out=8):
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import io, sym
    np.random.seed(0)
    mx.random.seed(0)
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=hidden, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=n_out, name="head")
    net = sym.SoftmaxOutput(net, name="softmax")
    x = np.random.randn(batch * 8, in_dim).astype(np.float32)
    y = np.random.randint(0, n_out, (batch * 8,)).astype(np.float32)
    it = io.NDArrayIter(x, y, batch_size=batch, label_name="softmax_label")
    mod = mx.mod.Module(net, context=mx.cpu())
    return mod, it


def fused_fit_probe(trials=3, epochs=2):
    """Seconds per fit epoch (best of `trials`) for one tracing state —
    the caller flips obs.trace around calls to this."""
    import incubator_mxnet_tpu as mx
    mod, it = _make_module()
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.initializer.Xavier())   # warm: compile here
    best = None
    for _ in range(trials):
        it.reset()
        t0 = time.perf_counter()
        mod.fit(it, num_epoch=epochs, optimizer="sgd",
                optimizer_params={"learning_rate": 0.01},
                force_init=False)
        dt = (time.perf_counter() - t0) / epochs
        best = dt if best is None else min(best, dt)
    return best


def _serving_fleet(n=3, in_dim=64, hidden=(128, 128)):
    """A serving fleet at example-model scale: the gate measures
    telemetry overhead against a request whose execute cost is in the
    production range (~ms), not a degenerate microbenchmark row — the
    artifact also records the ABSOLUTE added us/request."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import io, sym
    from incubator_mxnet_tpu.serving import (LocalReplica, ReplicaRouter,
                                             ServedModel)
    np.random.seed(0)
    net = sym.Variable("data")
    for i, h in enumerate(hidden):
        net = sym.FullyConnected(net, num_hidden=h, name=f"fc{i}")
        net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=8, name="head")
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (4, in_dim))],
             label_shapes=[io.DataDesc("softmax_label", (4,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    args, auxs = mod.get_params()
    reps = [LocalReplica(
        ServedModel(net, args, auxs, data_shapes=[("data", (1, in_dim))],
                    buckets=(1, 2, 4, 8, 16, 32), ctx=mx.cpu(), name="m"),
        replica_id=f"r{i}") for i in range(n)]
    router = ReplicaRouter(reps, health_interval_s=0.25,
                           health_deadline_s=5.0)
    return router, reps


def serving_probe(router, requests=256, concurrency=32, in_dim=64):
    """Seconds per request (closed loop, best effort at keeping the
    batcher busy) for the CURRENT tracing state.  Requests carry 4
    rows — the production-shaped case (multi-row requests riding the
    bucket ladder), not the degenerate 1-row microbenchmark."""
    import numpy as np
    x = np.random.randn(4, in_dim).astype(np.float32)
    t0 = time.perf_counter()
    done = 0
    while done < requests:
        futs = [router.submit({"data": x}, timeout_ms=30000)
                for _ in range(min(concurrency, requests - done))]
        for f in futs:
            f.result(60)
        done += len(futs)
    return (time.perf_counter() - t0) / requests


def overhead(off_s, on_s):
    if not off_s:
        return None
    return max((on_s - off_s) / off_s, 0.0)


def run(quick=False):
    from incubator_mxnet_tpu.obs import trace as obs_trace
    from incubator_mxnet_tpu.obs import metrics as obs_metrics
    from incubator_mxnet_tpu.obs.scrape import MetricsEndpoint, scrape
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import mxtrace

    report = {"gate_overhead": OVERHEAD_GATE, "quick": bool(quick)}
    tmp = tempfile.mkdtemp(prefix="mxobs_")
    span_path = os.path.join(tmp, "spans.jsonl")
    trials = 2 if quick else 3

    # The gated overhead number is DERIVED, not subtracted: (all-in
    # cost of one span, calibrated single-threaded in this process) x
    # (spans emitted per unit of work, measured in the traced run) /
    # (wall time per unit of work).  End-to-end off-vs-on wall deltas
    # ride along as evidence but are NOT the gate — on a shared CI
    # host their run-to-run noise (measured ~8%) swamps a 2% effect,
    # and in-hook wall timing under thread contention counts GIL
    # waits as telemetry cost.

    # -- 0. calibrate the per-span cost --------------------------------------
    obs_trace.enable(span_path)
    span_cost_s = obs_trace.calibrate_span_cost()
    report["span_cost_us"] = round(span_cost_s * 1e6, 2)

    # -- 1. overhead: fused-step hot path ------------------------------------
    obs_trace.disable()
    fit_off = fused_fit_probe(trials=trials)
    obs_trace.enable(span_path)
    e0, w0 = obs_trace.stats()["ended"], time.perf_counter_ns()
    fit_on = fused_fit_probe(trials=trials)
    fit_spans = obs_trace.stats()["ended"] - e0
    fit_wall_s = (time.perf_counter_ns() - w0) / 1e9
    obs_trace.disable()
    fit_self = fit_spans * span_cost_s / fit_wall_s
    fit_ovh = overhead(fit_off, fit_on)
    report["fused_step"] = {"off_s_per_epoch": round(fit_off, 5),
                            "on_s_per_epoch": round(fit_on, 5),
                            "spans": fit_spans,
                            "wall_delta": round(fit_ovh, 4),
                            "overhead": round(fit_self, 5),
                            "ok": fit_self < OVERHEAD_GATE}

    # -- 2. overhead: serving hot path ---------------------------------------
    n_req = 192 if quick else 256
    router, reps = _serving_fleet(3)
    try:
        serving_probe(router, requests=64)          # warm both paths
        obs_trace.disable()
        srv_off = min(serving_probe(router, n_req)
                      for _ in range(trials))
        obs_trace.enable(span_path)
        serving_probe(router, requests=32)
        e0, w0 = obs_trace.stats()["ended"], time.perf_counter_ns()
        per_req = [serving_probe(router, n_req) for _ in range(trials)]
        srv_spans = obs_trace.stats()["ended"] - e0
        srv_wall_s = (time.perf_counter_ns() - w0) / 1e9
        srv_on = min(per_req)
        n_total = n_req * trials
        spans_per_req = srv_spans / n_total
        srv_self = spans_per_req * span_cost_s / (srv_wall_s / n_total)
        srv_ovh = overhead(srv_off, srv_on)
        report["serving"] = {"off_s_per_req": round(srv_off, 6),
                             "on_s_per_req": round(srv_on, 6),
                             "spans_per_request": round(spans_per_req, 3),
                             "added_us_per_req": round(
                                 spans_per_req * span_cost_s * 1e6, 1),
                             "wall_delta": round(srv_ovh, 4),
                             "overhead": round(srv_self, 5),
                             "ok": srv_self < OVERHEAD_GATE}

        # -- 3. chaos burst: kill a replica mid-flight, all spans traced ----
        import numpy as np
        x = np.random.randn(1, 64).astype(np.float32)
        reps[0]._batcher.pause()
        futs = [router.submit({"data": x}, timeout_ms=30000)
                for _ in range(24)]
        time.sleep(0.05)
        reps[0].kill()
        results = [f.result(60) for f in futs]
        report["chaos_burst"] = {"requests": len(futs),
                                 "completed": len(results),
                                 "failovers": router.stats()["failovers"]}
    finally:
        router.shutdown(drain=True)
    obs_trace.flush()
    obs_trace.disable()

    # -- 4. merge + orphan gate ----------------------------------------------
    spans, events, chrome = mxtrace.load_inputs([span_path])
    merged_path = os.path.join(tmp, "merged_trace.json")
    trace, summary = mxtrace.merge(spans, events, chrome)
    with open(merged_path, "w") as f:
        json.dump(trace, f)
    report["trace"] = {"spans": summary["spans"],
                       "traces": summary["traces"],
                       "orphan_spans": summary["orphan_spans"],
                       "orphans": summary["orphans"],
                       "merged": merged_path,
                       "ok": summary["spans"] > 0
                       and summary["orphan_spans"] == 0}

    # -- 5. scrape validity over the transport -------------------------------
    import incubator_mxnet_tpu as mx
    kv = mx.kvstore.create("device")    # populates the kvstore namespace
    with MetricsEndpoint() as ep:
        snap = scrape(f"127.0.0.1:{ep.port}")
    del kv
    try:
        parsed = obs_metrics.parse_prometheus(snap["prom"])
        prom_ok, prom_err = True, None
    except ValueError as exc:
        parsed, prom_ok, prom_err = {}, False, str(exc)
    namespaces = sorted({k.split(".")[0] for k in snap["values"]})
    need = {"kvstore", "serving", "profiler"}
    report["scrape"] = {"metrics": len(snap["values"]),
                        "prom_samples": len(parsed),
                        "namespaces": namespaces,
                        "parse_error": prom_err,
                        "ok": prom_ok and need <= set(namespaces)}

    report["ok"] = all(report[k]["ok"]
                       for k in ("fused_step", "serving", "trace",
                                 "scrape"))
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="run_obs_gate", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--out", default=os.path.join(REPO, "OBS_REPORT.json"))
    args = ap.parse_args(argv)
    report = run(quick=args.quick)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        report["artifact"] = args.out
    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        print("obs gate: fused-step overhead %.2f%% (gate %.0f%%) %s"
              % (100 * report["fused_step"]["overhead"],
                 100 * OVERHEAD_GATE,
                 "OK" if report["fused_step"]["ok"] else "FAIL"))
        print("obs gate: serving overhead %.2f%% %s"
              % (100 * report["serving"]["overhead"],
                 "OK" if report["serving"]["ok"] else "FAIL"))
        print("obs gate: %d spans, %d orphans %s"
              % (report["trace"]["spans"],
                 report["trace"]["orphan_spans"],
                 "OK" if report["trace"]["ok"] else "FAIL"))
        print("obs gate: scrape %d metrics, namespaces %s %s"
              % (report["scrape"]["metrics"],
                 ",".join(report["scrape"]["namespaces"]),
                 "OK" if report["scrape"]["ok"] else "FAIL"))
        print("obs gate:", "PASS" if report["ok"] else "FAIL")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
