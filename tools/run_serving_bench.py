#!/usr/bin/env python
"""Serving benchmark: the closed-loop load generator for the serving SLO.

Two parts, one JSON artifact (next to BENCH_*.json):

* **batching** — the original single-server bench: QPS / p50 / p99 /
  batch occupancy at fixed offered loads, with a sequential
  `ServedModel.infer` baseline anchoring the dynamic-batching speedup.
* **router** — the multi-replica story (ROADMAP item 4): a closed-loop
  RAMP of client concurrency against a `ReplicaRouter`, doubling the
  offered load until p99 exceeds ``--slo-ms``; the **max sustainable
  QPS** is the fastest level that still met the SLO.  The ramp runs
  three fleets — 1 replica, N replicas, and N with one replica KILLED
  mid-ramp — so replica scaling and degraded (N-1) capacity are
  checkable numbers, plus a mixed-priority degradation run on the N-1
  fleet showing best-effort traffic shed FIRST while interactive p99
  holds inside the SLO (per-class metrics in the artifact).

Usage:
  python tools/run_serving_bench.py [--out SERVING_BENCH.json] [--json]
      [--requests N] [--loads 1,2,4,8] [--quick] [--slo-ms MS]
      [--replicas N] [--no-router]

``--json`` prints the artifact to stdout (the parity round's serving
stage consumes this); ``--out`` writes it to a file.  ``--quick`` shrinks
the run for CI embedding.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def build_checkpoint(prefix, in_dim, hidden):
    """Train-free model export: symbol JSON + params at `prefix`."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import sym, io
    net = sym.Variable("data")
    for i, h in enumerate(hidden):
        net = sym.FullyConnected(net, num_hidden=h, name=f"fc{i}")
        net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=10, name="head")
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (4, in_dim))],
             label_shapes=[io.DataDesc("softmax_label", (4,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    mod.save_checkpoint(prefix, 0)


def drive(server, name, n_threads, n_requests, in_dim, timeout_ms=None):
    """Offered load: `n_threads` clients, `n_requests` each.  Returns
    wall seconds; per-request stats land in the server's metrics."""
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((n_threads, 8, in_dim)).astype(np.float32)
    errors = []

    def client(t):
        for i in range(n_requests):
            x = xs[t, i % 8][None]
            try:
                server.predict(name, {"data": x}, timeout_ms=timeout_ms)
            except Exception as exc:  # count, don't die mid-bench
                errors.append(str(exc))

    threads = [threading.Thread(target=client, args=(t,),
                                name=f"mx-bench-client-{t}")
               for t in range(n_threads)]
    t0 = time.monotonic()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    return time.monotonic() - t0, errors


def _local_fleet(prefix, n, in_dim, buckets, latency_ms):
    """A router over n in-process replicas of the benched model."""
    import incubator_mxnet_tpu as mx
    reps = []
    for i in range(n):
        model = mx.serving.ServedModel.load(
            prefix, 0, data_shapes=[("data", (1, in_dim))],
            buckets=buckets, name="bench")
        reps.append(mx.serving.LocalReplica(
            model, replica_id=f"r{i}", max_queue_latency_ms=latency_ms))
    return mx.serving.ReplicaRouter(reps, health_interval_s=0.5), reps


def _ramp(router, in_dim, slo_ms, requests, max_level=64, kill_at_level=None,
          kill_fn=None, priority="interactive", miss_budget=None,
          on_level=None):
    """Closed-loop concurrency ramp: double the client count until p99
    breaks the SLO (or the cap).  Returns the per-level list and the
    max sustainable QPS (fastest level whose p99 met the SLO).

    ``miss_budget`` keeps the ramp alive through that many CONSECUTIVE
    SLO misses instead of stopping at the knee — the autoscale lane
    needs it, because a missed level is exactly when the fleet is
    recruiting capacity and the next level is expected to recover.
    ``on_level(entry)`` annotates each finished level (fleet size)."""
    x = np.random.default_rng(3).standard_normal(
        (1, in_dim)).astype(np.float32)
    levels = []
    sustainable = None
    misses = 0
    level = 1
    while level <= max_level:
        if kill_at_level is not None and level == kill_at_level \
                and kill_fn is not None:
            kill_fn()
            kill_fn = None   # once
        lat_ms = []
        errors = []
        lock = threading.Lock()

        def client():
            for _ in range(requests):
                t0 = time.monotonic()
                try:
                    router.predict({"data": x}, timeout_ms=30000,
                                   priority=priority)
                except Exception as exc:
                    with lock:
                        errors.append(str(exc))
                    continue
                with lock:
                    lat_ms.append((time.monotonic() - t0) * 1e3)

        threads = [threading.Thread(target=client,
                                    name=f"mx-bench-ramp-{level}-{i}")
                   for i in range(level)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        p99 = float(np.percentile(lat_ms, 99)) if lat_ms else None
        entry = {
            "concurrency": level,
            "requests": level * requests,
            "completed": len(lat_ms),
            "errors": len(errors),
            "qps": round(len(lat_ms) / wall, 1),
            "p50_ms": (round(float(np.percentile(lat_ms, 50)), 3)
                       if lat_ms else None),
            "p99_ms": round(p99, 3) if p99 is not None else None,
            "met_slo": bool(p99 is not None and p99 <= slo_ms),
        }
        if on_level is not None:
            on_level(entry)
        levels.append(entry)
        if entry["met_slo"]:
            sustainable = max(sustainable or 0.0, entry["qps"])
            misses = 0
        else:
            misses += 1
            if miss_budget is not None:
                if misses >= miss_budget:
                    break
            # past the knee — or never inside the SLO at all (a noisy
            # host): two straight misses end the ramp either way
            elif sustainable is not None or misses >= 2:
                break
        level *= 2
    return levels, sustainable


def _degradation_run(router, in_dim, slo_ms, requests, concurrency=8,
                     depth=16):
    """Mixed-priority traffic on a degraded fleet: interactive must hold
    the SLO while best-effort sheds first.  Each client PIPELINES
    ``depth`` async submits (an open-loop burst per thread), so the
    fleet sees real queue pressure — the regime the per-class shed
    policy exists for — without a thread per outstanding request.
    Returns per-class stats from the router's own reservoirs."""
    x = np.random.default_rng(4).standard_normal(
        (1, in_dim)).astype(np.float32)
    counts = {"interactive": [0, 0], "best_effort": [0, 0]}  # ok, err
    lock = threading.Lock()

    def client(cls, cls_depth):
        window = []

        def reap(f):
            try:
                f.result(60)
                with lock:
                    counts[cls][0] += 1
            except Exception:
                with lock:
                    counts[cls][1] += 1

        for _ in range(requests):
            try:
                window.append(router.submit({"data": x},
                                            timeout_ms=30000,
                                            priority=cls))
            except Exception:
                with lock:
                    counts[cls][1] += 1
                time.sleep(0.002)   # a shed reply means BACK OFF
            if len(window) >= cls_depth:
                reap(window.pop(0))
        for f in window:
            reap(f)

    # asymmetric offered load — the scenario the per-class policy
    # exists for: a modest interactive stream that must stay inside
    # SLO, drowned by a best-effort FLOOD that is the thing to shed.
    # The flood uses FEW deep-pipelined clients rather than many
    # shallow ones: identical queue pressure, far less client-side
    # scheduler noise polluting the measured tail.
    cls_cfg = {"interactive": (max(concurrency // 8, 2),
                               max(depth // 4, 2)),
               "best_effort": (max(concurrency // 8, 2), depth * 4)}

    def drive():
        threads = [threading.Thread(target=client, args=(cls, d),
                                    name=f"mx-bench-{cls}-{i}")
                   for cls, (n, d) in cls_cfg.items()
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    from incubator_mxnet_tpu.serving import ServingMetrics

    def reset_metrics():
        router.metrics = ServingMetrics(router.name)
        for cls in counts:
            counts[cls] = [0, 0]

    # baseline: the interactive stream ALONE on this fleet — what this
    # environment can deliver with nothing to shed.  The degradation
    # gate is relative to it (bounded inflation), not to an absolute
    # ms number a noisy CPU container could never hit
    n_i, d_i = cls_cfg["interactive"]
    base_threads = [threading.Thread(target=client,
                                     args=("interactive", d_i),
                                     name=f"mx-bench-base-{i}")
                    for i in range(n_i)]
    for t in base_threads:
        t.start()
    for t in base_threads:
        t.join()
    baseline = router.stats().get("classes", {}).get(
        "interactive", {}).get("p99_ms")
    # prime the mixed flood to steady state (the shed controller needs
    # observed latency before it can act), then measure with FRESH
    # reservoirs — the pre-shed transient is startup, not the degraded
    # steady state
    reset_metrics()
    drive()
    reset_metrics()
    drive()
    snap = router.stats()
    classes = snap.get("classes", {})
    inter = classes.get("interactive", {})
    be = classes.get("best_effort", {})
    # the protection bound: under a best-effort flood, interactive p99
    # may inflate at most 4x over its flood-free baseline (or the
    # absolute SLO when that is the larger allowance) — and the classes
    # must be clearly separated (interactive well under best-effort)
    bound_ms = max(slo_ms, 4.0 * baseline) if baseline else slo_ms
    return {
        "interactive": {"completed": counts["interactive"][0],
                        "errors": counts["interactive"][1],
                        "p99_ms": inter.get("p99_ms"),
                        "shed": inter.get("shed", 0)},
        "best_effort": {"completed": counts["best_effort"][0],
                        "errors": counts["best_effort"][1],
                        "p99_ms": be.get("p99_ms"),
                        "shed": be.get("shed", 0)},
        "interactive_baseline_p99_ms": baseline,
        "interactive_p99_bound_ms": round(bound_ms, 3),
        "interactive_met_slo": bool(
            inter.get("p99_ms") is not None
            and inter["p99_ms"] <= bound_ms
            and inter.get("shed", 0) == 0),
        "class_separation": bool(
            inter.get("p99_ms") is not None
            and be.get("p99_ms") is not None
            and inter["p99_ms"] * 2 <= be["p99_ms"]),
        "best_effort_shed_first": bool(
            be.get("shed", 0) >= inter.get("shed", 0)),
    }


def router_bench(prefix, in_dim, buckets, slo_ms, requests, n_replicas,
                 latency_ms, deg_concurrency=64):
    """The three-fleet ramp + the N-1 degradation run."""
    out = {"slo_ms": slo_ms, "replicas": n_replicas, "fleets": {}}
    # 1 replica vs N replicas: the scaling claim
    for label, n in (("1", 1), (str(n_replicas), n_replicas)):
        router, _reps = _local_fleet(prefix, n, in_dim, buckets,
                                     latency_ms)
        with router:
            levels, sustainable = _ramp(router, in_dim, slo_ms, requests)
        out["fleets"][f"replicas={label}"] = {
            "levels": levels, "max_sustainable_qps": sustainable}
    # N replicas with one killed mid-ramp: degraded capacity
    router, reps = _local_fleet(prefix, n_replicas, in_dim, buckets,
                                latency_ms)
    with router:
        levels, sustainable = _ramp(
            router, in_dim, slo_ms, requests, kill_at_level=4,
            kill_fn=reps[0].kill)
        out["fleets"][f"replicas={n_replicas},kill1"] = {
            "levels": levels, "max_sustainable_qps": sustainable,
            "killed_mid_ramp": reps[0].replica_id,
            "router": {k: router.stats()[k]
                       for k in ("failovers", "replicas_lost",
                                 "duplicates_suppressed")}}
    # the degradation gate: a FRESH N-1 fleet (fresh per-class
    # reservoirs, no ramp traffic mixed in) under pipelined overload,
    # shed thresholds tied to the SLO being defended
    router, reps = _local_fleet(prefix, n_replicas - 1, in_dim, buckets,
                                latency_ms)
    router.shed_ms = {"best_effort": slo_ms / 3.0, "batch": slo_ms,
                      "interactive": slo_ms * 20.0}
    with router:
        out["degradation"] = _degradation_run(
            router, in_dim, slo_ms, requests * 2,
            concurrency=deg_concurrency)
    return out


class _PacedModel:
    """Deterministic per-replica service rate for the autoscale lane:
    delegates to the real `ServedModel` but holds every batch execution
    for a fixed service time.  In-process replicas share the GIL and
    the host's cores, so raw XLA throughput on a small CPU model cannot
    separate 1 replica from N (the router lane's own numbers show it);
    pacing makes per-replica CAPACITY the bottleneck, so this lane
    measures what it claims to — the control loop recruiting and
    retiring capacity against a queue — not container CPU noise."""

    def __init__(self, model, service_s):
        self._model = model
        self._service_s = float(service_s)

    def run_bucket(self, arrs, bucket):
        time.sleep(self._service_s)
        return self._model.run_bucket(arrs, bucket)

    def __getattr__(self, name):
        return getattr(self._model, name)


def autoscale_bench(prefix, in_dim, buckets, slo_ms, requests, latency_ms,
                    max_replicas=4, service_ms=10.0):
    """The autoscale lane: max sustainable QPS under a concurrency ramp
    with NO manual resizing.  A fixed 1-replica fleet and a
    `FleetManager`-autoscaled fleet (floor 1, same SLO the ramp gates
    on) face the same doubling ramp of paced replicas; the autoscaled
    fleet must recruit its way to a higher sustainable QPS, then walk
    back down to the floor once the traffic stops — without thrashing
    on the way.

    Two ramp passes, mirroring the degradation run's prime-then-measure
    shape: a doubling ramp can outrun recruitment inside a single level
    (capacity cannot double in one cooldown), so pass 1 is the
    RECRUITMENT ramp — it rides through SLO misses on a miss budget
    while the fleet scales — and pass 2 measures the settled fleet's
    sustainable QPS.  Both passes' levels land in the artifact."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.serving import (FleetManager, InProcessHost,
                                             ReplicaSpec)
    lane_buckets = tuple(b for b in buckets if b <= 4) or (1, 2, 4)

    def paced_replica(rid):
        model = mx.serving.ServedModel.load(
            prefix, 0, data_shapes=[("data", (1, in_dim))],
            buckets=lane_buckets, name="bench")
        return mx.serving.LocalReplica(
            _PacedModel(model, service_ms / 1e3), replica_id=rid,
            max_queue_latency_ms=latency_ms)

    # the un-resized baseline: what one paced replica can sustain
    router = mx.serving.ReplicaRouter([paced_replica("fixed-0")],
                                      health_interval_s=0.5)
    with router:
        fixed_levels, fixed_qps = _ramp(router, in_dim, slo_ms, requests)

    # two logical hosts so placement exercises anti-affinity; the
    # actuation is in-process (every spinup shares the already-warm
    # program registry, so recruiting is zero-compile by construction)
    hosts = [InProcessHost("host-a", spawn=lambda spec, rid:
                           paced_replica(rid)),
             InProcessHost("host-b", spawn=lambda spec, rid:
                           paced_replica(rid))]
    spec = ReplicaSpec(data_shapes=[("data", (1, in_dim))], name="bench",
                       buckets=lane_buckets)
    # the idle threshold must sit ABOVE the paced service time: the
    # est-wait signal is floored by the response-latency EWMA, so a
    # threshold under the service floor could never see "idle"
    fleet = FleetManager(
        hosts, spec, name="bench-autoscale", target_replicas=1,
        min_replicas=1, max_replicas=max_replicas, slo_ms=slo_ms,
        tick_s=0.05, up_after_s=0.2, down_after_s=2.0, cooldown_s=0.6,
        idle_fraction=max(0.1, 3.0 * service_ms / slo_ms),
        host_heartbeat_s=0.5, host_deadline_s=30.0)

    def on_level(entry):
        entry["replicas"] = fleet.stats()["live_replicas"]

    returned_to_floor = False
    try:
        recruit_levels, _ = _ramp(fleet.router, in_dim, slo_ms, requests,
                                  miss_budget=3, on_level=on_level)
        peak = fleet.stats()["live_replicas"]
        levels, auto_qps = _ramp(fleet.router, in_dim, slo_ms, requests,
                                 miss_budget=3, on_level=on_level)
        # the ramp is over — the idle streak must retire the recruits
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = fleet.stats()
            if st["live_replicas"] <= fleet.autoscaler.min_replicas:
                returned_to_floor = True
                break
            time.sleep(0.2)
        st = fleet.stats()
    finally:
        fleet.shutdown(drain=False)
    events = st["scale_ups"] + st["scale_downs"]
    ratio = (round(auto_qps / fixed_qps, 2)
             if auto_qps and fixed_qps else None)
    return {
        "slo_ms": slo_ms,
        "service_ms_per_batch": service_ms,
        "buckets": list(lane_buckets),
        "replica_budget": [1, max_replicas],
        "fixed_1": {"levels": fixed_levels,
                    "max_sustainable_qps": fixed_qps},
        "recruitment": {"levels": recruit_levels,
                        "peak_replicas": peak},
        "autoscaled": {"levels": levels, "max_sustainable_qps": auto_qps},
        "scale_ups": st["scale_ups"],
        "scale_downs": st["scale_downs"],
        "clamped_at_max": st["signal"]["clamped_at_max"],
        "qps_ratio_vs_fixed_1": ratio,
        "gates": {
            # the acceptance bar: recruiting capacity must be worth
            # >= 1.5x what the hand-pinned single replica sustains
            "reaches_1_5x_fixed": bool(ratio is not None
                                       and ratio >= 1.5),
            "returned_to_floor": returned_to_floor,
            # no thrash: a clean run is <= (max-1) ups and the
            # matching downs; double that is the flap alarm
            "bounded_scale_events": events <= 2 * max_replicas,
        },
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="write the JSON artifact here")
    ap.add_argument("--json", action="store_true",
                    help="print the JSON artifact to stdout")
    ap.add_argument("--requests", type=int, default=64,
                    help="requests per client thread")
    ap.add_argument("--loads", default="1,2,4,8",
                    help="comma-separated client-thread counts")
    ap.add_argument("--latency-ms", type=float, default=2.0,
                    help="max_queue_latency_ms batching knob")
    ap.add_argument("--slo-ms", type=float, default=50.0,
                    help="p99 SLO for the router ramp (max sustainable "
                         "QPS is the fastest level inside it)")
    ap.add_argument("--replicas", type=int, default=3,
                    help="router fleet size for the ramp")
    ap.add_argument("--no-router", action="store_true",
                    help="skip the multi-replica ramp (batching only)")
    ap.add_argument("--quick", action="store_true",
                    help="small run for CI embedding")
    args = ap.parse_args(argv)
    if args.quick:
        args.requests = min(args.requests, 16)
        args.loads = "1,4"

    import incubator_mxnet_tpu as mx
    in_dim, hidden = 64, (128, 128)
    loads = [int(x) for x in args.loads.split(",") if x]
    artifact = {"model": f"mlp{in_dim}-" + "x".join(map(str, hidden)),
                "requests_per_client": args.requests,
                "max_queue_latency_ms": args.latency_ms,
                "backend": None, "levels": [], "sequential": None}

    with tempfile.TemporaryDirectory() as tmp:
        prefix = os.path.join(tmp, "bench")
        build_checkpoint(prefix, in_dim, hidden)
        import jax
        artifact["backend"] = jax.default_backend()

        buckets = (1, 2, 4, 8, 16, 32)
        model = mx.serving.ServedModel.load(
            prefix, 0, data_shapes=[("data", (1, in_dim))],
            buckets=buckets, name="bench")
        t0 = time.monotonic()
        model.warmup()
        artifact["warmup_s"] = round(time.monotonic() - t0, 3)
        artifact["buckets"] = list(buckets)

        # sequential single-request baseline (shared program cache: no
        # extra compiles)
        n_seq = args.requests * max(loads)
        x = np.random.default_rng(1).standard_normal(
            (1, in_dim)).astype(np.float32)
        t0 = time.monotonic()
        for _ in range(n_seq):
            model.infer({"data": x})
        seq_s = time.monotonic() - t0
        artifact["sequential"] = {"requests": n_seq,
                                  "qps": round(n_seq / seq_s, 1)}

        for level in loads:
            server = mx.serving.ModelServer(
                max_queue_latency_ms=args.latency_ms)
            server.load_model("bench", model=model, warmup=False)
            wall, errors = drive(server, "bench", level, args.requests,
                                 in_dim)
            snap = server.stats()["bench"]
            server.shutdown(drain=True)
            total = level * args.requests
            artifact["levels"].append({
                "offered_load": level,
                "requests": total,
                "wall_s": round(wall, 3),
                "qps": round(total / wall, 1),
                "p50_ms": (round(snap["p50_ms"], 3)
                           if snap["p50_ms"] is not None else None),
                "p99_ms": (round(snap["p99_ms"], 3)
                           if snap["p99_ms"] is not None else None),
                "batch_occupancy": round(snap["batch_occupancy"], 3),
                "avg_batch_rows": round(snap["avg_batch_rows"], 2),
                "errors": len(errors),
            })

        from incubator_mxnet_tpu.analysis import recompile
        sigs = recompile.signatures(model.audit_key)
        artifact["programs_compiled"] = len(sigs)
        artifact["post_warmup_recompiles"] = max(len(sigs) - len(buckets), 0)

        if not args.no_router:
            # the closed-loop multi-replica ramp (ROADMAP item 4):
            # local replicas share the bench model's program cache, so
            # fleet spin-up compiles nothing new
            artifact["router"] = router_bench(
                prefix, in_dim, buckets, args.slo_ms,
                max(args.requests // 2, 8) if args.quick else args.requests,
                args.replicas, args.latency_ms,
                deg_concurrency=16 if args.quick else 64)
            # the autoscale lane (ROADMAP item 5): the same ramp with
            # NO manual resizing — a FleetManager recruits capacity off
            # the admission est-wait signal and retires it afterwards
            artifact["autoscale"] = autoscale_bench(
                prefix, in_dim, buckets, args.slo_ms,
                max(args.requests // 2, 8) if args.quick else args.requests,
                args.latency_ms,
                max_replicas=min(args.replicas + 1, 4))

    out = json.dumps(artifact, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
        print("artifact:", args.out)
    if args.json or not args.out:
        print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
