#!/usr/bin/env python
"""Serving benchmark: QPS / p50 / p99 / batch occupancy vs. offered load.

Builds a small MLP, exports it through the classic checkpoint pair, loads
it into a `serving.ModelServer`, and drives it at increasing offered load
(client-thread counts), measuring each level with fresh `ServingMetrics`.
A sequential single-request baseline (the `ServedModel.infer` loop a
caller without the server would write) anchors the dynamic-batching
speedup claim.  Emits one JSON artifact so serving performance is
checkable evidence in the repo, mirroring `run_tpu_parity.py`.

Usage:
  python tools/run_serving_bench.py [--out SERVING_BENCH.json] [--json]
      [--requests N] [--loads 1,2,4,8] [--quick]

``--json`` prints the artifact to stdout (the parity round's serving
stage consumes this); ``--out`` writes it to a file.  ``--quick`` shrinks
the run for CI embedding.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def build_checkpoint(prefix, in_dim, hidden):
    """Train-free model export: symbol JSON + params at `prefix`."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import sym, io
    net = sym.Variable("data")
    for i, h in enumerate(hidden):
        net = sym.FullyConnected(net, num_hidden=h, name=f"fc{i}")
        net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=10, name="head")
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (4, in_dim))],
             label_shapes=[io.DataDesc("softmax_label", (4,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    mod.save_checkpoint(prefix, 0)


def drive(server, name, n_threads, n_requests, in_dim, timeout_ms=None):
    """Offered load: `n_threads` clients, `n_requests` each.  Returns
    wall seconds; per-request stats land in the server's metrics."""
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((n_threads, 8, in_dim)).astype(np.float32)
    errors = []

    def client(t):
        for i in range(n_requests):
            x = xs[t, i % 8][None]
            try:
                server.predict(name, {"data": x}, timeout_ms=timeout_ms)
            except Exception as exc:  # count, don't die mid-bench
                errors.append(str(exc))

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(n_threads)]
    t0 = time.monotonic()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    return time.monotonic() - t0, errors


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="write the JSON artifact here")
    ap.add_argument("--json", action="store_true",
                    help="print the JSON artifact to stdout")
    ap.add_argument("--requests", type=int, default=64,
                    help="requests per client thread")
    ap.add_argument("--loads", default="1,2,4,8",
                    help="comma-separated client-thread counts")
    ap.add_argument("--latency-ms", type=float, default=2.0,
                    help="max_queue_latency_ms batching knob")
    ap.add_argument("--quick", action="store_true",
                    help="small run for CI embedding")
    args = ap.parse_args(argv)
    if args.quick:
        args.requests = min(args.requests, 16)
        args.loads = "1,4"

    import incubator_mxnet_tpu as mx
    in_dim, hidden = 64, (128, 128)
    loads = [int(x) for x in args.loads.split(",") if x]
    artifact = {"model": f"mlp{in_dim}-" + "x".join(map(str, hidden)),
                "requests_per_client": args.requests,
                "max_queue_latency_ms": args.latency_ms,
                "backend": None, "levels": [], "sequential": None}

    with tempfile.TemporaryDirectory() as tmp:
        prefix = os.path.join(tmp, "bench")
        build_checkpoint(prefix, in_dim, hidden)
        import jax
        artifact["backend"] = jax.default_backend()

        buckets = (1, 2, 4, 8, 16, 32)
        model = mx.serving.ServedModel.load(
            prefix, 0, data_shapes=[("data", (1, in_dim))],
            buckets=buckets, name="bench")
        t0 = time.monotonic()
        model.warmup()
        artifact["warmup_s"] = round(time.monotonic() - t0, 3)
        artifact["buckets"] = list(buckets)

        # sequential single-request baseline (shared program cache: no
        # extra compiles)
        n_seq = args.requests * max(loads)
        x = np.random.default_rng(1).standard_normal(
            (1, in_dim)).astype(np.float32)
        t0 = time.monotonic()
        for _ in range(n_seq):
            model.infer({"data": x})
        seq_s = time.monotonic() - t0
        artifact["sequential"] = {"requests": n_seq,
                                  "qps": round(n_seq / seq_s, 1)}

        for level in loads:
            server = mx.serving.ModelServer(
                max_queue_latency_ms=args.latency_ms)
            server.load_model("bench", model=model, warmup=False)
            wall, errors = drive(server, "bench", level, args.requests,
                                 in_dim)
            snap = server.stats()["bench"]
            server.shutdown(drain=True)
            total = level * args.requests
            artifact["levels"].append({
                "offered_load": level,
                "requests": total,
                "wall_s": round(wall, 3),
                "qps": round(total / wall, 1),
                "p50_ms": (round(snap["p50_ms"], 3)
                           if snap["p50_ms"] is not None else None),
                "p99_ms": (round(snap["p99_ms"], 3)
                           if snap["p99_ms"] is not None else None),
                "batch_occupancy": round(snap["batch_occupancy"], 3),
                "avg_batch_rows": round(snap["avg_batch_rows"], 2),
                "errors": len(errors),
            })

        from incubator_mxnet_tpu.analysis import recompile
        sigs = recompile.signatures(model.audit_key)
        artifact["programs_compiled"] = len(sigs)
        artifact["post_warmup_recompiles"] = max(len(sigs) - len(buckets), 0)

    out = json.dumps(artifact, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out + "\n")
        print("artifact:", args.out)
    if args.json or not args.out:
        print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
