#!/usr/bin/env python
"""Data-plane bench & CI gate (BENCH_IO.json).

Measures the production io tier (io_plane.py h2d staging ring +
per-host sharded readers + uint8-on-the-wire) and gates it:

1. **h2d probe** — host memcpy bandwidth (the physical ceiling), the
   BLOCKING ``device_put`` baseline (what the pre-ring loop paid — the
   13.8 MB/s BENCH_r05 number on the dev tunnel), and the PIPELINED
   staging-ring rate (transfers on the ``mx-io-h2d`` thread, the
   consumer pops device-resident batches).
2. **real vs synthetic** — the same convnet (uint8 NHWC in, in-graph
   `ImageNormalize` head) trained from an in-memory iterator vs the
   full RecordIO decode pipeline; real-data steady img/s must be
   ≥ 0.98x synthetic (the pipeline hides behind compute).
3. **zero steady recompiles** — the unified program cache's compile
   counter must not move across the measurement window with the ring
   enabled (the ring's staged batches keep the dispatch signature
   fixed).
4. **tsan sweep** — a throwaway subprocess drives the ring + decode
   pool + a mini fit under ``MXNET_TSAN=1``; the dump must hold zero
   findings (the new ``mx-io-*`` threads are race/lock-order clean).

Gates (BENCH_IO.json `gates`):
  pipelined_h2d_10x_baseline   pipelined ≥ 10 × 13.8 MB/s
  pipelined_within_10x_memcpy  pipelined × 10 ≥ memcpy probe
  real_ge_098x_synthetic       real img/s ≥ 0.98 × synthetic img/s
  zero_steady_recompiles       no compiles inside the steady window
  tsan_clean                   zero sanitizer findings

Exit code 0 iff every gate passes.  ``--quick`` shrinks the model and
windows for the run_tpu_parity `io` stage.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# the pre-ring blocking h2d number this PR attacks (BENCH_r05)
BASELINE_BLOCKING_MBPS = 13.8

MEAN = (123.68, 116.78, 103.94)
STD = (58.4, 57.1, 57.4)


from bench_io import h2d_probe  # noqa: E402  (the shared probe)


def _convnet(dtype="float32"):
    """uint8-NHWC-in convnet with the in-graph normalize head — the
    uint8-on-the-wire shape both lanes train."""
    import incubator_mxnet_tpu as mx
    data = mx.sym.Variable("data")
    x = mx.sym.ImageNormalize(data, mean=MEAN, std=STD,
                              input_layout="NHWC", output_layout="NCHW",
                              dtype=dtype)
    x = mx.sym.Convolution(x, num_filter=16, kernel=(3, 3), pad=(1, 1),
                           name="conv0")
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type="max")
    x = mx.sym.Convolution(x, num_filter=32, kernel=(3, 3), pad=(1, 1),
                           name="conv1")
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.Flatten(x)
    x = mx.sym.FullyConnected(x, num_hidden=16, name="fc0")
    return mx.sym.SoftmaxOutput(x, name="softmax")


class _Probe:
    """Batch callback: steady img/s over [warm, warm+steps) plus the
    program-cache compile counter at the window edges."""

    def __init__(self, warm, steps, batch):
        self.warm, self.steps, self.batch = warm, steps, batch
        self.t0 = None
        self.img_s = None
        self.compiles = None

    @staticmethod
    def _compile_count():
        from incubator_mxnet_tpu import compile as _compile
        try:
            return int(_compile.stats()["counters"]["compiles"])
        except Exception:
            return -1

    def __call__(self, param):
        if param.nbatch == self.warm:
            param.eval_metric.get()     # sync the window edge
            self.t0 = time.perf_counter()
            self._c0 = self._compile_count()
        elif param.nbatch == self.warm + self.steps:
            param.eval_metric.get()
            dt = time.perf_counter() - self.t0
            self.img_s = self.batch * self.steps / dt
            self.compiles = self._compile_count() - self._c0


def _fit(mod_sym, it, batch, warm, steps):
    import incubator_mxnet_tpu as mx
    mx.random.seed(0)
    mod = mx.mod.Module(mod_sym, context=mx.cpu(),
                        label_names=("softmax_label",))
    probe = _Probe(warm, steps, batch)
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.01, "momentum": 0.9},
            eval_metric="acc",
            initializer=mx.initializer.Xavier(),
            batch_end_callback=probe, kvstore=None)
    assert probe.img_s is not None, "probe window missed (too few batches)"
    return probe


def train_lanes(batch, image, warm, steps):
    """Synthetic (in-memory uint8 batches) vs real (RecordIO decode
    pipeline) img/s on the identical model + signature."""
    import incubator_mxnet_tpu as mx
    from bench_io import build_corpus
    n = batch * (warm + steps + 9)   # one block past the window, no tail
    rng = np.random.RandomState(0)
    sym = _convnet()

    data = rng.randint(0, 255, (n, image, image, 3)).astype(np.uint8)
    labels = rng.randint(0, 16, n).astype("f4")
    synth_it = mx.io.NDArrayIter(data, labels, batch_size=batch,
                                 label_name="softmax_label")
    synth = _fit(sym, synth_it, batch, warm, steps)

    d = tempfile.mkdtemp(prefix="bench_io_")
    rec = os.path.join(d, "corpus.rec")
    build_corpus(rec, n=n, size=image + 8)
    real_it = mx.io.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, image, image), batch_size=batch,
        rand_crop=True, rand_mirror=True,
        mean_r=MEAN[0], mean_g=MEAN[1], mean_b=MEAN[2],
        std_r=STD[0], std_g=STD[1], std_b=STD[2],
        preprocess_threads=4, label_width=1, device_augment="auto")
    real = _fit(sym, real_it, batch, warm, steps)
    real_it.close()

    from incubator_mxnet_tpu import io_plane
    io_stats = io_plane.stats()
    return {
        "synthetic_img_s": round(synth.img_s, 2),
        "real_img_s": round(real.img_s, 2),
        "real_vs_synthetic": round(real.img_s / synth.img_s, 4),
        "steady_recompiles": {"synthetic": synth.compiles,
                              "real": real.compiles},
        "ring": {k: round(v, 4) if isinstance(v, float) else v
                 for k, v in io_stats.items()},
    }


_TSAN_CHILD = """
import numpy as np
import incubator_mxnet_tpu as mx
rng = np.random.RandomState(0)
n, b = 64, 8
it = mx.io.NDArrayIter(rng.randn(n, 12).astype('f4'),
                       rng.randint(0, 4, n).astype('f4'), batch_size=b)
data = mx.sym.Variable('data')
x = mx.sym.FullyConnected(data, num_hidden=16, name='fc0')
x = mx.sym.Activation(x, act_type='relu')
x = mx.sym.FullyConnected(x, num_hidden=4, name='fc1')
sym = mx.sym.SoftmaxOutput(x, name='softmax')
mod = mx.mod.Module(sym, context=mx.cpu())
mod.fit(it, num_epoch=2, optimizer='sgd', eval_metric='acc',
        initializer=mx.initializer.Xavier(), kvstore=None)
"""


def tsan_sweep():
    """Drive the ring + a mini fit in a throwaway process under
    MXNET_TSAN=1; zero findings in the dump = clean."""
    log = os.path.join(tempfile.mkdtemp(prefix="io_tsan_"), "tsan.json")
    env = dict(os.environ, MXNET_TSAN="1", MXNET_TSAN_LOG=log,
               JAX_PLATFORMS="cpu", MXNET_IO_RING="1")
    proc = subprocess.run([sys.executable, "-c", _TSAN_CHILD], cwd=REPO,
                          capture_output=True, text=True, timeout=600,
                          env=env)
    out = {"rc": proc.returncode}
    try:
        with open(log) as f:
            dumps = [json.loads(ln) for ln in f.read().splitlines()
                     if ln.strip()]
        found = [fi for dmp in dumps for fi in dmp.get("findings", [])]
        out["findings"] = len(found)
        out["detail"] = [
            {k: fi.get(k) for k in ("code", "severity", "location")}
            for fi in found][:20]
    except Exception as exc:
        out["findings"] = None
        out["dump_error"] = repr(exc)
    if proc.returncode != 0:
        out["tail"] = proc.stderr.strip()[-500:]
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small model + short windows (CI stage)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--out", default=os.path.join(REPO, "BENCH_IO.json"))
    args = ap.parse_args()

    if args.quick:
        batch, image, warm, steps = 16, 48, 8, 24
        probe_batch, probe_image = 32, 128
    else:
        batch, image, warm, steps = 32, 64, 8, 48
        probe_batch, probe_image = 64, 224

    t0 = time.time()
    result = {"quick": bool(args.quick),
              "baseline_blocking_MBps": BASELINE_BLOCKING_MBPS}
    result["h2d"] = h2d_probe(probe_batch, probe_image)
    result["train"] = train_lanes(batch, image, warm, steps)
    result["tsan"] = tsan_sweep()

    h2d = result["h2d"]
    tr = result["train"]
    gates = {
        "pipelined_h2d_10x_baseline":
            h2d["pipelined_MBps"] >= 10 * BASELINE_BLOCKING_MBPS,
        "pipelined_within_10x_memcpy":
            h2d["pipelined_MBps"] * 10 >= h2d["memcpy_MBps"],
        "real_ge_098x_synthetic": tr["real_vs_synthetic"] >= 0.98,
        "zero_steady_recompiles":
            tr["steady_recompiles"]["synthetic"] == 0 and
            tr["steady_recompiles"]["real"] == 0,
        "tsan_clean": result["tsan"].get("rc") == 0 and
            result["tsan"].get("findings") == 0,
    }
    result["gates"] = gates
    result["passed"] = all(gates.values())
    result["duration_s"] = round(time.time() - t0, 1)

    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    if args.json:
        print(json.dumps(result))
    else:
        print(json.dumps(result, indent=1))
    print("artifact:", args.out, file=sys.stderr)
    return 0 if result["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
