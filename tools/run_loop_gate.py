#!/usr/bin/env python
"""LOOP_REPORT gate: one clean in-process train-to-serve loop.

Runs the whole continuous loop in a single process with no injected
faults — the "sunny day" counterpart of ``run_chaos.py --loop`` — and
gates the invariants the loop subsystem promises when nothing goes
wrong:

* the publisher's cadence yields a stream of registry versions and the
  controller promotes at least ``MIN_PROMOTIONS`` of them (canary →
  rolling fleet swap) while training is still running;
* zero canary rejections — a clean loop never trips the gate;
* a traffic thread hammers the fleet throughout: zero admitted requests
  lost across every swap;
* zero new XLA programs after warmup — every promotion is a pure
  weight swap (params are call arguments, never baked constants);
* every promotion's ``loop.freshness_lag_s`` (data-shard watermark →
  model live) is within the freshness SLO, and the gauge is visible in
  the obs scrape plane.

Writes ``LOOP_REPORT.json``; exit code 0 iff every gate holds.

Usage::

    python tools/run_loop_gate.py [--out LOOP_REPORT.json]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

MIN_PROMOTIONS = 3
FRESHNESS_SLO_S = 120.0


def _build(tmp):
    """Module + 2-LocalReplica fleet booted from the module's own
    initial parameters (a step-0 elastic checkpoint)."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import checkpoint as ckpt, sym
    from incubator_mxnet_tpu.serving import LocalReplica, ReplicaRouter
    from tools import loop_trainer as lt

    np.random.seed(7)
    mx.random.seed(7)
    mod = lt._build_module()
    mod.bind(data_shapes=[("data", (4, lt.N_FEAT))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.initializer.Xavier())
    args, auxs = mod.get_params()
    args = {k: np.asarray(v.asnumpy()) for k, v in args.items()}
    auxs = {k: np.asarray(v.asnumpy()) for k, v in (auxs or {}).items()}

    arrays = {"arg:" + k: v for k, v in args.items()}
    arrays.update({"aux:" + k: v for k, v in auxs.items()})
    mgr = ckpt.CheckpointManager(os.path.join(tmp, "boot"), keep_last=4,
                                 async_snapshots=False)
    mgr.snapshot(arrays=arrays, step=0, epoch=0, nbatch=0,
                 meta={"health": {"status": "healthy"}}, sync=True)
    mgr.close()
    boot_ck = os.path.join(tmp, "boot", "ckpt-%010d" % 0)

    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=64, name="fc0")
    net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=lt.N_CLASS, name="head")
    net = sym.SoftmaxOutput(net, name="softmax")
    models = [mx.serving.ServedModel(
        net, {k: mx.nd.array(v) for k, v in args.items()},
        {k: mx.nd.array(v) for k, v in auxs.items()},
        data_shapes=[("data", (1, lt.N_FEAT))], buckets=(1, 2, 4),
        ctx=mx.cpu(), name=f"m{i}") for i in range(2)]
    reps = [LocalReplica(m, replica_id=f"w{i}")
            for i, m in enumerate(models)]
    router = ReplicaRouter(reps, name="loop-gate", health_interval_s=5.0)
    return mod, router, models, boot_ck


def _traffic(router, stop, counts):
    import numpy as np
    rng = np.random.default_rng(9)
    x = (rng.standard_normal((2, 16)) * 0.1).astype(np.float32)
    while not stop.is_set():
        try:
            router.submit({"data": x}, timeout_ms=30000).result(60)
            counts["ok"] += 1
        except Exception as exc:
            counts["errors"].append(repr(exc))
        time.sleep(0.002)


def run(out_path, quiet=False):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import loop as mxloop
    from incubator_mxnet_tpu.checkpoint.manifest import atomic_write_json
    from incubator_mxnet_tpu.loop import CanaryRejectedError
    from incubator_mxnet_tpu.obs import metrics as obs_metrics
    from tools import loop_trainer as lt

    tmp = tempfile.mkdtemp(prefix="loop-gate-")
    t0 = time.time()
    try:
        mod, router, models, boot_ck = _build(tmp)
        reg = mxloop.ModelRegistry(os.path.join(tmp, "registry"))
        pub = mxloop.CheckpointPublisher(
            reg, os.path.join(tmp, "ckpt"), publish_steps=8)
        ctl = mxloop.LoopController(
            router, reg, lt.holdout_batch(), canary_tol=1.0,
            poll_interval_s=0.1, freshness_slo_s=FRESHNESS_SLO_S,
            incumbent_checkpoint=boot_ck)

        # warm the request path before baselining program counts: the
        # gate certifies SWAPS compile nothing, not that warmup is free
        hold_x = lt.holdout_batch()[0]
        for _ in range(3):
            router.submit(hold_x, timeout_ms=30000).result(60)
        programs0 = [m.program_count() for m in models]

        counts = {"ok": 0, "errors": []}
        stop = threading.Event()
        threads = [threading.Thread(target=_traffic,
                                    args=(router, stop, counts),
                                    daemon=True) for _ in range(2)]
        for t in threads:
            t.start()

        promoted, rejected = [], []

        def gate_cb(param):
            try:
                res = ctl.poll_once()
            except CanaryRejectedError as exc:
                rejected.append(exc.version)
                return
            if res.get("status") == "promoted":
                promoted.append(res)

        # ~96 records / bs 4 -> 24 steps/epoch, 2 epochs = 48 gsteps;
        # publish cadence 8 + checkpoint period 4 -> ~6 versions
        rec = os.path.join(tmp, "shard.rec")
        lt.write_shard(rec, n=96)
        it = lt.RecordFloatIter(rec, batch_size=4)
        try:
            pub.fit(mod, it, num_epoch=2, optimizer="sgd",
                    optimizer_params={"learning_rate": 0.05},
                    eval_metric="acc",
                    initializer=mx.initializer.Xavier(),
                    checkpoint_period=4, batch_end_callback=gate_cb)
        finally:
            it.close()
        # drain: promote whatever the trainer published after the last
        # callback poll
        for _ in range(10):
            try:
                res = ctl.poll_once()
            except CanaryRejectedError as exc:
                rejected.append(exc.version)
                continue
            if res.get("status") == "promoted":
                promoted.append(res)
            elif res.get("status") == "idle":
                break

        stop.set()
        for t in threads:
            t.join(timeout=30)
        programs1 = [m.program_count() for m in models]

        cstats = ctl.stats()
        lags = [float(r["freshness_lag_s"]) for r in promoted]
        snap = obs_metrics.registry().collect()
        gates = {
            "promotions_reached": cstats.get("promotions", 0)
            >= MIN_PROMOTIONS,
            "zero_rejections": cstats.get("canary_rejections", 0) == 0
            and not rejected,
            "zero_lost_requests": counts["ok"] > 0
            and not counts["errors"],
            "zero_swap_compiles": programs0 == programs1,
            "freshness_within_slo": bool(lags)
            and max(lags) <= FRESHNESS_SLO_S
            and cstats.get("freshness_slo_met") == 1,
            "freshness_gauge_scraped": "loop.freshness_lag_s" in snap,
        }
        report = {
            "gates": gates,
            "all_passed": all(gates.values()),
            "promotions": [int(r["version"]) for r in promoted],
            "max_freshness_lag_s": max(lags) if lags else None,
            "freshness_slo_s": FRESHNESS_SLO_S,
            "requests_served": counts["ok"],
            "request_errors": counts["errors"][:5],
            "programs_per_replica": programs1,
            "controller": cstats,
            "publisher": pub.stats(),
            "registry": reg.stats(),
            "duration_s": round(time.time() - t0, 1),
        }
    finally:
        try:
            router.shutdown(drain=False)
        except Exception:
            pass
        shutil.rmtree(tmp, ignore_errors=True)

    if out_path:
        atomic_write_json(out_path, report)
    if not quiet:
        print("loop gate: all_passed=%s gates=%s promotions=%s "
              "lag=%.2fs served=%d (%.1fs) -> %s"
              % (report["all_passed"], report["gates"],
                 report["promotions"],
                 report["max_freshness_lag_s"] or -1.0,
                 report["requests_served"], report["duration_s"],
                 out_path or "<stdout>"))
        if not out_path:
            print(json.dumps(report, indent=1, sort_keys=True))
    return 0 if report["all_passed"] else 1


def main(argv=None):
    ap = argparse.ArgumentParser(prog="run_loop_gate",
                                 description=__doc__)
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "LOOP_REPORT.json"))
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    return run(args.out, quiet=args.quiet)


if __name__ == "__main__":
    sys.exit(main())
