#!/usr/bin/env python
"""Chaos runner: the tier-1 dist + serving tests under canned fault
schedules, with a JSON artifact of what was injected and what survived.

Each schedule sets ``MXNET_FAULTS`` (a seeded, deterministic fault spec —
see resilience/faults.py) and ``MXNET_FAULTS_LOG`` for the pytest process
AND every worker subprocess it spawns, runs the selected tests, then
aggregates the fault log: faults fired by site/kind, retries, reconnects,
and the final pass/fail counts.  The tests are the SAME tests that gate
normal PRs — the chaos claim is exactly "the functional contract holds
while the transport is being actively sabotaged".

Usage: python tools/run_chaos.py [--quick] [--json] [--out PATH]
    --quick   bounded test selection (the run_tpu_parity.py stage)
    --json    print only the JSON artifact on stdout
    --out     also write the artifact to PATH (default CHAOS_REPORT.json)

Exit status: 0 when every schedule's tests passed.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# seeded schedules: same seed -> same per-process fault sequence, so a
# red chaos run reproduces locally with the spec string alone
SCHEDULES = {
    "flaky-connect": "seed=11;transport.connect:refuse(n=2)",
    "dropped-pushes": "seed=12;transport.send:drop(p=0.3,cmd=push,n=4)",
    "slow-peers": ("seed=13;server.dispatch:slow(ms=30,p=0.05);"
                   "serving.execute:slow(ms=10,p=0.2)"),
}

QUICK_TESTS = [
    "tests/test_dist.py::test_dist_sync_multiprocess[2-0]",
    "tests/test_dist.py::test_dist_sync_sharded_servers",
    "tests/test_serving.py::test_concurrent_clients_correct_and_ordered",
    "tests/test_serving.py::test_unload_drains_without_dropping",
]

FULL_TESTS = QUICK_TESTS + [
    "tests/test_dist.py::test_dist_sync_multiprocess[4-0]",
    "tests/test_dist.py::test_dist_sync_three_servers_uneven_ranges",
    "tests/test_dist.py::test_dist_compression_packs_the_wire",
    "tests/test_serving.py::test_drain_on_shutdown_completes_in_flight",
    "tests/test_serving.py::test_backpressure_bounded_queue",
]


def _counts(output):
    counts = {"passed": 0, "failed": 0, "errors": 0}
    for key, word in (("passed", "passed"), ("failed", "failed"),
                      ("errors", "errors?")):
        m = re.search(r"(\d+) %s\b" % word, output)
        if m:
            counts[key] = int(m.group(1))
    return counts


def _read_fault_log(path):
    """Aggregate one schedule's MXNET_FAULTS_LOG (all processes append)."""
    agg = {"faults": 0, "by_site_kind": {}, "retries": 0, "reconnects": 0}
    try:
        with open(path) as f:
            for line in f:
                try:
                    event = json.loads(line)
                except ValueError:
                    continue
                kind = event.get("event")
                if kind == "fault":
                    agg["faults"] += 1
                    key = "%s:%s" % (event.get("site"), event.get("kind"))
                    agg["by_site_kind"][key] = \
                        agg["by_site_kind"].get(key, 0) + 1
                elif kind == "retry":
                    agg["retries"] += 1
                elif kind == "reconnect":
                    agg["reconnects"] += 1
    except OSError:
        pass
    return agg


def run_schedule(name, spec, tests, quiet=False):
    log_fd, log_path = tempfile.mkstemp(prefix="chaos-%s-" % name,
                                        suffix=".jsonl")
    os.close(log_fd)
    env = dict(os.environ, MXNET_FAULTS=spec, MXNET_FAULTS_LOG=log_path,
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep +
               os.environ.get("PYTHONPATH", ""))
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "-q", "--tb=line",
             "-p", "no:cacheprovider"] + tests,
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=1200)
        rc, output = proc.returncode, proc.stdout + proc.stderr
    except subprocess.TimeoutExpired as exc:
        # a hung schedule is a RESULT (the worst one) — record it with
        # whatever the fault log captured instead of crashing the run
        rc = -1
        output = "TIMEOUT after %ds\n%s" % (exc.timeout,
                                            (exc.stdout or "")[-1200:])
    result = {
        "schedule": name,
        "spec": spec,
        "rc": rc,
        **_counts(output),
        "duration_s": round(time.time() - t0, 1),
        **_read_fault_log(log_path),
        "tail": "\n".join(output.strip().splitlines()[-6:])[-1200:],
    }
    os.unlink(log_path)
    if not quiet:
        print("chaos[%s]: rc=%d passed=%d failed=%d faults=%d retries=%d "
              "reconnects=%d (%.1fs)" %
              (name, result["rc"], result["passed"], result["failed"],
               result["faults"], result["retries"], result["reconnects"],
               result["duration_s"]), file=sys.stderr)
    return result


def main(argv=None):
    ap = argparse.ArgumentParser(prog="run_chaos", description=__doc__)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "CHAOS_REPORT.json"))
    args = ap.parse_args(argv)
    tests = QUICK_TESTS if args.quick else FULL_TESTS

    runs = [run_schedule(name, spec, tests, quiet=args.as_json)
            for name, spec in SCHEDULES.items()]
    artifact = {
        "quick": args.quick,
        "tests": tests,
        "schedules": runs,
        "total_faults": sum(r["faults"] for r in runs),
        "total_retries": sum(r["retries"] for r in runs),
        "all_passed": all(r["rc"] == 0 for r in runs),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
    if args.as_json:
        slim = dict(artifact)
        for r in slim["schedules"]:
            r.pop("tail", None)
        print(json.dumps(slim))
    else:
        print("chaos: %d schedule(s), %d faults fired, %d retries, "
              "all_passed=%s -> %s" %
              (len(runs), artifact["total_faults"],
               artifact["total_retries"], artifact["all_passed"], args.out))
    return 0 if artifact["all_passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
