#!/usr/bin/env python
"""Chaos runner: the tier-1 dist + serving tests under canned fault
schedules, with a JSON artifact of what was injected and what survived.

Each schedule sets ``MXNET_FAULTS`` (a seeded, deterministic fault spec —
see resilience/faults.py) and ``MXNET_FAULTS_LOG`` for the pytest process
AND every worker subprocess it spawns, runs the selected tests, then
aggregates the fault log: faults fired by site/kind, retries, reconnects,
and the final pass/fail counts.  The tests are the SAME tests that gate
normal PRs — the chaos claim is exactly "the functional contract holds
while the transport is being actively sabotaged".

Pod mode (``--pod``) runs the ELASTIC schedules instead: a root
parameter server (the pod coordinator) plus three real worker processes
mid-`Module.fit` under the supervisor, sabotaged per rank — heartbeat
drops that must NOT trip false host loss, one host SIGKILLed mid-fit
(survivors must detect it, shrink, and resume from the checkpoint), and
one hung collective (the watchdog must convert the stall into a
`CollectiveTimeoutError` and the whole pod must recover).  The artifact
(``CHAOS_POD.json``) embeds every surviving worker's
`JobSupervisor.stats()` dict — heartbeats, watchdog timeouts, hosts
lost, and the PR 5 kvstore retry/breaker counters.

Serving mode (``--serving``) runs the MULTI-REPLICA schedules over a
real `ReplicaRouter` fronting three subprocess replica workers (spawned
with a shared program-cache dir, so replicas 2-3 must spin up with ZERO
XLA compiles): one worker SIGKILLed mid-flight (zero accepted requests
lost, zero duplicate executions — certified from the survivors'
executed-rid logs), a health-probe drop burst (suspicion, never a false
eviction), a full rolling weight-swap under traffic (zero dropped
requests, zero post-warmup compiles — certified via worker compile-
cache stats), and a torn swap (clean abort, fleet keeps serving,
re-issue completes).  The artifact is ``CHAOS_SERVING.json``.

Training-guardian mode (``--train``) runs the NUMERICAL-HEALTH
schedules: an injected non-finite gradient (the guardian must refuse
the update in-graph and continue deterministically — two identical
seeded runs end bit-identical), an injected loss spike (the guardian
must roll back to the last healthy checkpoint and end bit-identical to
a clean reference run that skipped the same quarantined window), and an
injected corrupt record (the io tier must substitute/skip it, count it,
and quarantine it so a resumed iterator never reads it again).  Every
schedule additionally certifies ZERO unified-program-cache compiles
during recovery (the live/in-memory tier serves every rebuilt program).
The artifact is ``CHAOS_TRAIN.json``.

Decode mode (``--decode``) runs the CONTINUOUS-BATCHING schedules over
a real `ReplicaRouter` fronting two in-process `DecodeReplica`s (one
shared cached-jit program space, so the second replica warms with ZERO
compiles): a steady-state mixed-ladder sweep (zero compiles, zero
recompile-auditor findings across arbitrary prompt/budget arrival
orders) and one replica SIGKILLed mid-decode — every admitted sequence
must be replayed on the survivor (the prefill re-derives the lost KV
state from the prompt) with zero losses and zero duplicate deliveries.
The artifact is ``CHAOS_DECODE.json``.

Loop mode (``--loop``) runs the CONTINUOUS TRAIN-TO-SERVE schedules: a
real trainer process (tools/loop_trainer.py) publishing guardian-healthy
checkpoints into a shared `ModelRegistry` while a 2-replica remote fleet
promotes them through the `LoopController`'s canary gate under live
traffic.  One schedule corrupts a training shard mid-loop
(``io.corrupt_record`` payload damage + an injected loss spike: the
guardian rolls back, the publisher fences the disowned window, and the
fleet must NEVER serve a fenced or rejected version, lose zero admitted
requests, compile nothing during swaps, and go live on the next clean
version inside the freshness SLO); one publishes a healthy-stamped but
weight-sabotaged checkpoint (the serving-side canary must reject it,
swap the canary replica back, stamp it rejected — durably, never
retried); one tears a publish mid-commit (the truncated manifest must
be invisible and a clean re-publish must promote).  The artifact is
``CHAOS_LOOP.json``.

Usage: python tools/run_chaos.py [--quick] [--pod] [--serving] [--train]
                                 [--decode] [--loop] [--json] [--out PATH]
    --quick   bounded test selection (the run_tpu_parity.py stage)
    --pod     run the elastic pod schedules (writes CHAOS_POD.json)
    --serving run the multi-replica router schedules
              (writes CHAOS_SERVING.json)
    --train   run the training-guardian schedules
              (writes CHAOS_TRAIN.json)
    --decode  run the continuous-batching decode schedules
              (writes CHAOS_DECODE.json)
    --loop    run the train-to-serve loop schedules
              (writes CHAOS_LOOP.json)
    --json    print only the JSON artifact on stdout
    --out     also write the artifact to PATH (default CHAOS_REPORT.json,
              CHAOS_POD.json with --pod, CHAOS_SERVING.json with
              --serving, CHAOS_TRAIN.json with --train)

Exit status: 0 when every schedule's tests passed.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# seeded schedules: same seed -> same per-process fault sequence, so a
# red chaos run reproduces locally with the spec string alone
SCHEDULES = {
    "flaky-connect": "seed=11;transport.connect:refuse(n=2)",
    "dropped-pushes": "seed=12;transport.send:drop(p=0.3,cmd=push,n=4)",
    "slow-peers": ("seed=13;server.dispatch:slow(ms=30,p=0.05);"
                   "serving.execute:slow(ms=10,p=0.2)"),
}

QUICK_TESTS = [
    "tests/test_dist.py::test_dist_sync_multiprocess[2-0]",
    "tests/test_dist.py::test_dist_sync_sharded_servers",
    "tests/test_serving.py::test_concurrent_clients_correct_and_ordered",
    "tests/test_serving.py::test_unload_drains_without_dropping",
]

FULL_TESTS = QUICK_TESTS + [
    "tests/test_dist.py::test_dist_sync_multiprocess[4-0]",
    "tests/test_dist.py::test_dist_sync_three_servers_uneven_ranges",
    "tests/test_dist.py::test_dist_compression_packs_the_wire",
    "tests/test_serving.py::test_drain_on_shutdown_completes_in_flight",
    "tests/test_serving.py::test_backpressure_bounded_queue",
]


def _counts(output):
    counts = {"passed": 0, "failed": 0, "errors": 0}
    for key, word in (("passed", "passed"), ("failed", "failed"),
                      ("errors", "errors?")):
        m = re.search(r"(\d+) %s\b" % word, output)
        if m:
            counts[key] = int(m.group(1))
    return counts


def _read_fault_log(path):
    """Aggregate one schedule's MXNET_FAULTS_LOG (all processes append)."""
    agg = {"faults": 0, "by_site_kind": {}, "retries": 0, "reconnects": 0}
    try:
        with open(path) as f:
            for line in f:
                try:
                    event = json.loads(line)
                except ValueError:
                    continue
                kind = event.get("event")
                if kind == "fault":
                    agg["faults"] += 1
                    key = "%s:%s" % (event.get("site"), event.get("kind"))
                    agg["by_site_kind"][key] = \
                        agg["by_site_kind"].get(key, 0) + 1
                elif kind == "retry":
                    agg["retries"] += 1
                elif kind == "reconnect":
                    agg["reconnects"] += 1
    except OSError:
        pass
    return agg


def run_schedule(name, spec, tests, quiet=False):
    log_fd, log_path = tempfile.mkstemp(prefix="chaos-%s-" % name,
                                        suffix=".jsonl")
    os.close(log_fd)
    env = dict(os.environ, MXNET_FAULTS=spec, MXNET_FAULTS_LOG=log_path,
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep +
               os.environ.get("PYTHONPATH", ""))
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "-q", "--tb=line",
             "-p", "no:cacheprovider"] + tests,
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=1200)
        rc, output = proc.returncode, proc.stdout + proc.stderr
    except subprocess.TimeoutExpired as exc:
        # a hung schedule is a RESULT (the worst one) — record it with
        # whatever the fault log captured instead of crashing the run
        rc = -1
        output = "TIMEOUT after %ds\n%s" % (exc.timeout,
                                            (exc.stdout or "")[-1200:])
    result = {
        "schedule": name,
        "spec": spec,
        "rc": rc,
        **_counts(output),
        "duration_s": round(time.time() - t0, 1),
        **_read_fault_log(log_path),
        "tail": "\n".join(output.strip().splitlines()[-6:])[-1200:],
    }
    os.unlink(log_path)
    if not quiet:
        print("chaos[%s]: rc=%d passed=%d failed=%d faults=%d retries=%d "
              "reconnects=%d (%.1fs)" %
              (name, result["rc"], result["passed"], result["failed"],
               result["faults"], result["retries"], result["reconnects"],
               result["duration_s"]), file=sys.stderr)
    return result


# -- pod schedules: elastic multi-host supervision under sabotage -------------
# three workers mid-Module.fit; faults are injected PER RANK so each
# schedule is one deterministic pod failure story
POD_SCHEDULES = {
    # lossy control network: a burst of 3 consecutive dropped heartbeats
    # per host (0.6s silence under the 1.2s deadline) must not trip
    # false host loss — and the drops must verifiably fire
    "pod-hb-drops": {"faults": {"*": "seed=21;heartbeat.send:drop(at=2-4)"},
                     "killed": None, "min_faults": 3},
    # whole-host SIGKILL mid-fit: survivors must detect the loss within
    # the heartbeat deadline, convert the stalled round into a
    # CollectiveTimeoutError, shrink to world 2, and resume
    "pod-host-crash": {"faults": {"2": "seed=22;host.step:kill(at=4)"},
                       "killed": 2},
    # hung collective on one rank: every watchdog fires (no host is
    # dead), the full pod shrinks-in-place and resumes — no indefinite
    # hang anywhere
    "pod-hung-collective": {
        "faults": {"1": "seed=23;collective.dispatch:hang(at=9)"},
        "killed": None},
}

# the worker subprocess body is tools/pod_worker.py — ONE copy shared
# with tests/test_supervisor.py so the chaos artifact and the acceptance
# test exercise the identical protocol
POD_WORKER_PATH = os.path.join(REPO, "tools", "pod_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_pod_schedule(name, schedule, quiet=False):
    """One pod schedule: root server (coordinator) + 3 supervised workers
    mid-fit, faults injected per rank.  Returns the result dict with
    per-worker outcomes and every survivor's JobSupervisor.stats()."""
    n_workers = 3
    log_fd, log_path = tempfile.mkstemp(prefix="chaos-%s-" % name,
                                        suffix=".jsonl")
    os.close(log_fd)
    ckpt_dir = tempfile.mkdtemp(prefix="chaos-%s-ckpt-" % name)
    port = _free_port()
    base_env = dict(
        os.environ,
        DMLC_PS_ROOT_URI="127.0.0.1", DMLC_PS_ROOT_PORT=str(port),
        DMLC_NUM_WORKER=str(n_workers), DMLC_ROLE="worker",
        MXNET_KVSTORE_COLLECTIVE="0",
        # fast pod clocks: detection in ~1s, watchdog in 3s, so a whole
        # schedule (including shrink + resume) fits a CI budget
        MXNET_SUPERVISOR_HEARTBEAT_S="0.2",
        MXNET_SUPERVISOR_DEADLINE_S="1.2",
        MXNET_SUPERVISOR_COLLECTIVE_TIMEOUT_S="3.0",
        MXNET_SUPERVISOR_SHRINK_BARRIER_S="10.0",
        MXNET_PS_RECONNECT_WAIT="1.0",
        MXNET_FAULTS_LOG=log_path,
        POD_CKPT_DIR=ckpt_dir,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    base_env.pop("MXNET_FAULTS", None)
    t0 = time.time()
    server = subprocess.Popen(
        [sys.executable, "-m", "incubator_mxnet_tpu.dist.server"],
        env=dict(base_env), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, cwd=REPO)
    procs = []
    for r in range(n_workers):
        env = dict(base_env, DMLC_RANK=str(r))
        spec = schedule["faults"].get(str(r)) or schedule["faults"].get("*")
        if spec:
            env["MXNET_FAULTS"] = spec
        procs.append(subprocess.Popen(
            [sys.executable, POD_WORKER_PATH], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, cwd=REPO))
    workers = []
    hung = False
    for r, p in enumerate(procs):
        try:
            out = p.communicate(timeout=240)[0].decode()
        except subprocess.TimeoutExpired:
            # a hung worker is the exact failure this subsystem exists to
            # prevent — record it as the worst result, don't hang the run
            hung = True
            p.kill()
            out = (p.communicate()[0] or b"").decode() + "\nHUNG (killed)"
        sup_stats = None
        sha = None
        for line in out.splitlines():
            if line.startswith("SUPSTATS "):
                try:
                    sup_stats = json.loads(line[len("SUPSTATS "):])
                except ValueError:
                    pass
            elif line.startswith("PARAMS_SHA "):
                sha = line.split()[1]
        workers.append({"rank": r, "rc": p.returncode,
                        "params_sha": sha, "supervisor": sup_stats,
                        "tail": "\n".join(out.strip().splitlines()[-5:])
                                [-800:]})
    server.kill()
    server.communicate()
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    killed = schedule["killed"]
    survivors = [w for w in workers if w["rank"] != killed]
    fault_agg = _read_fault_log(log_path)
    passed = (not hung
              and all(w["rc"] == 0 for w in survivors)
              and all(w["params_sha"] is not None for w in survivors)
              and len({w["params_sha"] for w in survivors}) == 1
              and (killed is None or workers[killed]["rc"] == 137)
              and fault_agg["faults"] >= schedule.get("min_faults", 1))
    result = {
        "schedule": name,
        "specs": schedule["faults"],
        "killed_rank": killed,
        "workers": workers,
        "duration_s": round(time.time() - t0, 1),
        **fault_agg,
        "passed": passed,
    }
    os.unlink(log_path)
    if not quiet:
        print("chaos[%s]: passed=%s rcs=%s faults=%d (%.1fs)" %
              (name, passed, [w["rc"] for w in workers],
               result["faults"], result["duration_s"]), file=sys.stderr)
    return result


def _spawn_pod(port, n_workers, ckpt_dir, log_path, faults_by_rank=None,
               resume=False, scaling=True):
    """Launch n supervised pod workers against the coordinator at
    `port`; returns (procs, outs).  One copy of the env recipe shared
    by the scaling schedule's chaos and control lanes."""
    base_env = dict(
        os.environ,
        DMLC_PS_ROOT_URI="127.0.0.1", DMLC_PS_ROOT_PORT=str(port),
        DMLC_NUM_WORKER=str(n_workers), DMLC_ROLE="worker",
        MXNET_KVSTORE_COLLECTIVE="0",
        MXNET_SUPERVISOR_HEARTBEAT_S="0.2",
        MXNET_SUPERVISOR_DEADLINE_S="1.2",
        MXNET_SUPERVISOR_COLLECTIVE_TIMEOUT_S="3.0",
        MXNET_SUPERVISOR_SHRINK_BARRIER_S="10.0",
        MXNET_PS_RECONNECT_WAIT="1.0",
        MXNET_FAULTS_LOG=log_path,
        POD_CKPT_DIR=ckpt_dir,
        POD_RESUME="1" if resume else "0",
        POD_SCALING="1" if scaling else "0",
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    base_env.pop("MXNET_FAULTS", None)
    base_env.pop("MXNET_SUPERVISOR_EPOCH", None)
    procs = []
    for r in range(n_workers):
        env = dict(base_env, DMLC_RANK=str(r))
        spec = (faults_by_rank or {}).get(str(r))
        if spec:
            env["MXNET_FAULTS"] = spec
        procs.append(subprocess.Popen(
            [sys.executable, POD_WORKER_PATH], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, cwd=REPO))
    outs = []
    for p in procs:
        try:
            outs.append(p.communicate(timeout=240)[0].decode())
        except subprocess.TimeoutExpired:
            p.kill()
            outs.append((p.communicate()[0] or b"").decode()
                        + "\nHUNG (killed)")
    return procs, outs


def _pod_server(port, n_workers):
    env = dict(os.environ,
               DMLC_PS_ROOT_URI="127.0.0.1", DMLC_PS_ROOT_PORT=str(port),
               DMLC_NUM_WORKER=str(n_workers),
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep +
               os.environ.get("PYTHONPATH", ""))
    return subprocess.Popen(
        [sys.executable, "-m", "incubator_mxnet_tpu.dist.server"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        cwd=REPO)


def run_pod_scaling_schedule(quiet=False):
    """The scale-meets-resilience composition gate: a 3-worker
    SUPERVISED scaling sweep (per-world-size throughput curve recorded
    by every worker), one host SIGKILLed mid-sweep — survivors must
    shrink to world 2, resume from the last committed checkpoint, and
    COMPLETE the curve (points at world 3 AND world 2) — then a control
    lane: an uninterrupted 2-worker run resumed from the same
    checkpoint must end with bit-identical params."""
    t0 = time.time()
    checks = {}
    log_fd, log_path = tempfile.mkstemp(prefix="chaos-pod-scaling-",
                                        suffix=".jsonl")
    os.close(log_fd)
    ckpt_dir = tempfile.mkdtemp(prefix="chaos-pod-scaling-ckpt-")
    control_dir = ckpt_dir + "-control"
    curves = []
    try:
        # lane 1 — chaos: rank 2 dies at its 4th step, mid-sweep
        port = _free_port()
        server = _pod_server(port, 3)
        procs, outs = _spawn_pod(
            port, 3, ckpt_dir, log_path,
            faults_by_rank={"2": "seed=24;host.step:kill(at=4)"})
        server.kill()
        server.communicate()
        shas, resume_step = set(), None
        for r in (0, 1):
            m = re.search(r"PARAMS_SHA (\w+)", outs[r])
            shas.add(m.group(1) if m else None)
            m = re.search(r"SCALING (.*)", outs[r])
            curves.append(json.loads(m.group(1)) if m else {})
            m = re.search(r"resuming from .*\(step (\d+),", outs[r])
            if m:
                resume_step = int(m.group(1))
        checks["killed_host_rc_137"] = procs[2].returncode == 137
        checks["survivors_completed"] = all(
            p.returncode == 0 for p in procs[:2])
        checks["survivors_agree"] = len(shas) == 1 and None not in shas
        # the curve COMPLETED across the shrink: every survivor holds a
        # world-3 point (pre-kill) and a world-2 point (post-resume)
        checks["curve_spans_shrink"] = all(
            set(c) >= {"2", "3"} and
            all(pt["steps"] > 0 for pt in c.values())
            for c in curves)
        # lane 2 — control: clean 2-worker resume from the SAME
        # checkpoint the survivors resumed from (prune newer snapshots)
        checks["resume_step_found"] = resume_step is not None
        if resume_step is not None:
            shutil.copytree(ckpt_dir, control_dir)
            for entry in os.listdir(control_dir):
                cm = re.match(r"ckpt-(\d+)$", entry)
                if cm and int(cm.group(1)) > resume_step:
                    shutil.rmtree(os.path.join(control_dir, entry))
            port = _free_port()
            server = _pod_server(port, 2)
            cprocs, couts = _spawn_pod(port, 2, control_dir, log_path,
                                       resume=True)
            server.kill()
            server.communicate()
            cshas = set()
            for r in (0, 1):
                m = re.search(r"PARAMS_SHA (\w+)", couts[r])
                cshas.add(m.group(1) if m else None)
            checks["control_completed"] = all(
                p.returncode == 0 for p in cprocs)
            checks["bit_identical_vs_clean_shrunk"] = (
                len(cshas) == 1 and None not in cshas and cshas == shas)
    finally:
        fault_agg = _read_fault_log(log_path)
        os.unlink(log_path)
        shutil.rmtree(ckpt_dir, ignore_errors=True)
        shutil.rmtree(control_dir, ignore_errors=True)
    bools = [v for v in checks.values() if isinstance(v, bool)]
    result = {
        "schedule": "pod-scaling",
        "specs": {"2": "seed=24;host.step:kill(at=4)"},
        "killed_rank": 2,
        "checks": checks,
        "curves": curves,
        "workers": [],
        **fault_agg,
        "duration_s": round(time.time() - t0, 1),
        "passed": bool(bools) and all(bools),
    }
    if not quiet:
        print("chaos[pod-scaling]: passed=%s checks=%s (%.1fs)" %
              (result["passed"], checks, result["duration_s"]),
              file=sys.stderr)
    return result


def run_pod(as_json=False, out_path=None):
    runs = [run_pod_schedule(name, sched, quiet=as_json)
            for name, sched in POD_SCHEDULES.items()]
    try:
        runs.append(run_pod_scaling_schedule(quiet=as_json))
    except Exception as exc:
        runs.append({"schedule": "pod-scaling", "passed": False,
                     "workers": [], "error": repr(exc)})
    artifact = {
        "schedules": runs,
        "all_passed": all(r["passed"] for r in runs),
        "supervisor_stats": {
            r["schedule"]: [w["supervisor"] for w in r["workers"]
                            if w["supervisor"] is not None]
            for r in runs},
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    if as_json:
        slim = {"all_passed": artifact["all_passed"],
                "schedules": [{k: v for k, v in r.items()
                               if k not in ("workers",)}
                              for r in runs],
                "supervisor_stats": artifact["supervisor_stats"]}
        print(json.dumps(slim))
    else:
        print("chaos pod: %d schedule(s), all_passed=%s -> %s" %
              (len(runs), artifact["all_passed"], out_path))
    return 0 if artifact["all_passed"] else 1


# -- serving schedules: the replica router under sabotage ---------------------
# a real 3-replica fleet (subprocess workers) behind an in-process
# ReplicaRouter; router-side faults are seeded so every run replays the
# same story.  Each schedule returns the acceptance verdicts the README
# failure matrix promises.

def _export_mlp(tmp):
    """One tiny served model exported as a classic checkpoint pair;
    returns (module, prefix, worker env with a shared program-cache
    dir).  Shared by the serving and fleet schedules."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import sym, io
    np.random.seed(0)
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=64, name="fc0")
    net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=8, name="head")
    net = sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("data", (4, 16))],
             label_shapes=[io.DataDesc("softmax_label", (4,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    prefix = os.path.join(tmp, "m")
    mod.save_checkpoint(prefix, 0)
    env = {"MXNET_PROGRAM_CACHE_DIR": os.path.join(tmp, "pcache"),
           "JAX_PLATFORMS": "cpu"}
    return mod, prefix, env


def _serving_fleet(tmp, n=3, buckets=(1, 2, 4), health_deadline_s=3.0):
    """(router, replicas, model artifacts) — a spawned remote fleet
    warming from one shared program-cache dir."""
    import incubator_mxnet_tpu as mx
    mod, prefix, env = _export_mlp(tmp)
    reps = [mx.serving.RemoteReplica.spawn(
        prefix=prefix, epoch=0, data_shapes=[("data", (1, 16))],
        buckets=buckets, name="m", replica_id="w%d" % i, env=env)
        for i in range(n)]
    router = mx.serving.ReplicaRouter(
        reps, health_interval_s=0.2, health_deadline_s=health_deadline_s)
    return router, reps, (mod, prefix)


def _drive_router(router, n_threads=4, per=40, kill_at=None,
                  kill_fn=None, priority="interactive", timeout_ms=30000):
    """Closed-loop traffic; optionally fire `kill_fn` once `kill_at`
    requests were accepted.  Returns (ok_count, errors)."""
    results, errors = [], []
    accepted = [0]
    fired = [False]
    lock = threading.Lock()

    def client():
        for _ in range(per):
            try:
                f = router.submit({"data": _drive_router._x},
                                  timeout_ms=timeout_ms,
                                  priority=priority)
                with lock:
                    accepted[0] += 1
                    if kill_at is not None and accepted[0] == kill_at \
                            and not fired[0]:
                        fired[0] = True
                        kill_fn()
                results.append(f.result(60))
            except Exception as exc:   # a lost request is the FINDING
                errors.append(repr(exc))

    import numpy as np
    _drive_router._x = np.random.default_rng(5).standard_normal(
        (2, 16)).astype(np.float32)
    threads = [threading.Thread(target=client,
                                name=f"mx-chaos-client-{i}")
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return len(results), errors


def _survivor_rids(reps, skip=()):
    rids = []
    for r in reps:
        if r.replica_id in skip:
            continue
        rids += r.stats().get("executed_rids", [])
    return rids


def run_serving_schedule(name, tmp, quiet=False):
    """One serving schedule; returns a result dict with `passed`."""
    from incubator_mxnet_tpu.resilience import faults as _f
    import incubator_mxnet_tpu as mx
    t0 = time.time()
    checks = {}
    router, reps, (mod, prefix) = _serving_fleet(tmp)
    try:
        # zero-compile fleet spin-up evidence (all schedules)
        checks["spinup_zero_compiles"] = all(
            r.ready_info.get("compiles") == 0 for r in reps[1:])
        if name == "replica-kill":
            _f.configure("seed=41")   # trace/log only; the kill is real
            ok, errors = _drive_router(router, kill_at=60,
                                       kill_fn=reps[1].kill)
            rids = _survivor_rids(reps, skip=("w1",))
            st = router.stats()
            checks.update(
                zero_lost=(ok == 160 and not errors),
                zero_duplicate_execution=(len(rids) == len(set(rids))
                                          and st["duplicates_suppressed"]
                                          == 0),
                replica_declared_dead=(st["replicas_lost"] == 1),
                failovers=st["failovers"])
        elif name == "probe-drop-burst":
            _f.configure("seed=42;replica.health:drop(at=2-6)")
            ok, errors = _drive_router(router, per=30)
            time.sleep(1.0)   # let the probe schedule play out
            st = router.stats()
            drops = [e for e in _f.trace()
                     if e.get("site") == "replica.health"]
            checks.update(
                zero_lost=(ok == 120 and not errors),
                drops_fired=(len(drops) >= 3),
                no_false_eviction=(st["replicas_lost"] == 0))
        elif name in ("rolling-swap", "torn-swap"):
            args, auxs = mod.get_params()
            ckroot = os.path.join(tmp, "ckpts-" + name)
            mgr = mx.checkpoint.CheckpointManager(ckroot,
                                                  async_snapshots=False)
            arrays = {"arg:%s" % k: v.asnumpy() * 2.0
                      for k, v in args.items()}
            arrays.update({"aux:%s" % k: v.asnumpy()
                           for k, v in auxs.items()})
            mgr.snapshot(arrays=arrays, step=1)
            mgr.close()
            if name == "torn-swap":
                _f.configure("seed=43;replica.swap:torn(at=2)")
            else:
                _f.configure("seed=44")
            base = [r.stats() for r in reps]
            swap_err = [None]

            def do_swap():
                try:
                    router.swap_weights(checkpoint_dir=ckroot)
                except Exception as exc:
                    swap_err[0] = repr(exc)

            swapper = threading.Thread(target=do_swap,
                                       name="mx-chaos-swapper")
            swapper.start()
            ok, errors = _drive_router(router, per=30)
            swapper.join(120)
            if name == "torn-swap":
                # the roll must ABORT cleanly with the fleet serving;
                # clearing the fault and re-issuing finishes it
                checks["aborted_cleanly"] = (
                    swap_err[0] is not None and "ABORTED" in swap_err[0])
                _f.configure("seed=44")
                router.swap_weights(checkpoint_dir=ckroot)
            else:
                checks["swap_completed"] = swap_err[0] is None
            after = [r.stats() for r in reps]
            versions = [s.get("version") for s in after]
            compiles = [
                (s.get("cache") or {}).get("compiles", 0) -
                (b.get("cache") or {}).get("compiles", 0)
                for b, s in zip(base, after)]
            checks.update(
                zero_lost=(ok == 120 and not errors),
                all_swapped=(all(v and v >= 1 for v in versions)),
                zero_swap_compiles=(all(c == 0 for c in compiles)),
                # the compiled ladder is untouched by the swap (the
                # program-count face of the recompile-auditor claim)
                programs_stable=(all(s.get("programs") == 3
                                     for s in after)),
                versions=versions)
        else:
            raise ValueError("unknown serving schedule %r" % name)
        errs = errors[:5] if errors else []
    finally:
        try:
            router.shutdown(drain=False)
        except Exception:
            pass
        for r in reps:
            try:
                r.kill()
            except Exception:
                pass
        _f.clear()
    bools = [v for v in checks.values() if isinstance(v, bool)]
    result = {
        "schedule": name,
        "checks": checks,
        "errors": errs,
        "duration_s": round(time.time() - t0, 1),
        "passed": bool(bools) and all(bools),
    }
    if not quiet:
        print("chaos[serving/%s]: passed=%s checks=%s (%.1fs)" %
              (name, result["passed"], checks, result["duration_s"]),
              file=sys.stderr)
    return result


def run_serving(as_json=False, out_path=None):
    runs = []
    for name in ("replica-kill", "probe-drop-burst", "rolling-swap",
                 "torn-swap"):
        tmp = tempfile.mkdtemp(prefix="chaos-serving-%s-" % name)
        try:
            runs.append(run_serving_schedule(name, tmp, quiet=as_json))
        except Exception as exc:
            runs.append({"schedule": name, "passed": False,
                         "error": repr(exc)})
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    artifact = {
        "schedules": runs,
        "all_passed": all(r["passed"] for r in runs),
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    if as_json:
        print(json.dumps(artifact))
    else:
        print("chaos serving: %d schedule(s), all_passed=%s -> %s" %
              (len(runs), artifact["all_passed"], out_path))
    return 0 if artifact["all_passed"] else 1


# -- fleet schedule: a whole HOST dies under mixed-priority load --------------
# two real host daemons (serving.hostd process groups), two replicas
# each behind a FleetManager; one host's ENTIRE process group is
# SIGKILLed mid-ramp.  The acceptance story: zero admitted interactive
# requests lost, interactive p99 inside its SLO band while best-effort
# sheds first, the fleet backfilled to target on the surviving host,
# and every backfill spinup certified zero-compile off the shared
# program cache.

def run_fleet_schedule(tmp, quiet=False, slo_ms=150.0):
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.resilience import faults as _f
    from incubator_mxnet_tpu.serving import AgentHost, FleetManager, \
        ReplicaSpec
    t0 = time.time()
    checks = {}
    detail = {}
    errs = []
    _f.configure("seed=61")   # trace/log only; the host kill is real
    _mod, prefix, env = _export_mlp(tmp)
    spec = ReplicaSpec(data_shapes=[("data", (1, 16))], name="m",
                       prefix=prefix, epoch=0, buckets=(1, 2, 4), env=env)
    x = np.random.default_rng(6).standard_normal((2, 16)).astype(
        np.float32)
    # setup INSIDE the try: the daemons are their own process groups
    # (start_new_session), so a host-b launch or FleetManager failure
    # must still reach the finally that kills host-a — an orphaned
    # daemon would outlive the whole chaos run
    hosts = []
    fleet = None
    try:
        hosts.append(AgentHost.launch_local("host-a", env=env))
        hosts.append(AgentHost.launch_local("host-b", env=env))
        # max == target: this schedule certifies host-loss BACKFILL
        # (the autoscale-growth story is the bench's), and every extra
        # breach-driven cold spawn during the measured window is a
        # python+jax import storm polluting the p99 the gate is about
        fleet = FleetManager(
            hosts, spec, name="chaos-fleet", target_replicas=4,
            min_replicas=4, max_replicas=4, slo_ms=slo_ms, tick_s=0.1,
            up_after_s=0.3, down_after_s=600.0, cooldown_s=0.5,
            host_heartbeat_s=0.2, host_deadline_s=1.5)
        router = fleet.router
        # the degradation policy under capacity loss: best-effort is
        # the shock absorber, interactive sheds only at queue collapse
        router.shed_ms = {"best_effort": slo_ms / 4.0, "batch": slo_ms,
                          "interactive": slo_ms * 100.0}
        st = fleet.stats()
        checks["spread_over_hosts"] = (
            sorted(set(st["placement"].values())) == ["host-a", "host-b"])
        # initial spinup: first worker compiles the ladder cold, every
        # later one loads it from the shared disk tier
        ups = [e for e in st["events"] if e["action"] == "scale_up"]
        checks["spinup_zero_compiles_after_first"] = all(
            e.get("spinup_compiles") == 0 for e in ups[1:])

        # phase 0 — flood-free interactive baseline (the SLO band is
        # relative to what THIS machine can deliver, like the serving
        # bench's degradation gate)
        def interactive_client(n, out):
            for _ in range(n):
                t1 = time.monotonic()
                try:
                    router.predict({"data": x}, timeout_ms=30000,
                                   priority="interactive")
                    out["lat_ms"].append((time.monotonic() - t1) * 1e3)
                except Exception as exc:
                    out["errors"].append(repr(exc))

        base = {"lat_ms": [], "errors": []}
        base_threads = [threading.Thread(target=interactive_client,
                                         args=(40, base),
                                         name="mx-chaos-fleet-base-%d" % i)
                        for i in range(3)]
        for t in base_threads:
            t.start()
        for t in base_threads:
            t.join()
        baseline_p99 = float(np.percentile(base["lat_ms"], 99)) \
            if base["lat_ms"] else None
        bound_ms = max(slo_ms, 4.0 * baseline_p99) \
            if baseline_p99 else slo_ms

        # phase 1 — mixed-priority ramp with the host kill mid-flight
        from incubator_mxnet_tpu.serving import ServingMetrics
        router.metrics = ServingMetrics(router.name)   # fresh reservoirs
        inter = {"lat_ms": [], "errors": []}
        be_done, be_shed = [0], [0]
        stop_be = threading.Event()
        accepted = [0]
        killed = [False]
        lock = threading.Lock()

        def interactive_ramp(n):
            for _ in range(n):
                t1 = time.monotonic()
                try:
                    f = router.submit({"data": x}, timeout_ms=30000,
                                      priority="interactive")
                except Exception as exc:
                    inter["errors"].append("admit: " + repr(exc))
                    continue
                with lock:
                    accepted[0] += 1
                    if accepted[0] == 60 and not killed[0]:
                        killed[0] = True
                        hosts[1].kill()   # SIGKILL the host process group
                try:
                    f.result(60)
                    inter["lat_ms"].append((time.monotonic() - t1) * 1e3)
                except Exception as exc:   # an admitted loss is a FINDING
                    inter["errors"].append(repr(exc))

        def best_effort_flood():
            # PIPELINED (open-loop) flood, the serving bench's
            # degradation pattern: a deep async window per client is
            # what builds real queue pressure on a fast model — a
            # closed-loop client could never push est-wait over the
            # best-effort shed threshold
            window = []

            def reap(f):
                try:
                    f.result(60)
                    with lock:
                        be_done[0] += 1
                except Exception:
                    with lock:
                        be_shed[0] += 1

            while not stop_be.is_set():
                try:
                    window.append(router.submit({"data": x},
                                                timeout_ms=30000,
                                                priority="best_effort"))
                except Exception:
                    with lock:
                        be_shed[0] += 1
                    time.sleep(0.002)   # a shed reply means BACK OFF
                if len(window) >= 64:
                    reap(window.pop(0))
            for f in window:
                reap(f)

        # 1000 interactive samples: at most ~4-8 requests can be caught
        # in the kill's failover window (closed loop, 4 threads), and
        # the p99 of a 1000-sample run has its cutoff at 10 — so the
        # gate measures the steady degraded tail, not the coin-flip of
        # whether a ~300ms failover spike lands inside a 2.8-request
        # p99 cutoff (bimodal flake at 280 samples)
        threads = [threading.Thread(target=interactive_ramp, args=(250,),
                                    name="mx-chaos-fleet-inter-%d" % i)
                   for i in range(4)]
        threads += [threading.Thread(target=best_effort_flood,
                                     name="mx-chaos-fleet-be-%d" % i)
                    for i in range(2)]
        for t in threads:
            t.start()
        for t in threads[:4]:
            t.join()
        # keep the flood up until the fleet has backfilled, so the SLO
        # claim covers the degraded window end to end
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = fleet.stats()
            if st["hosts_lost"] == 1 and st["backfills"] >= 1:
                break
            time.sleep(0.1)
        stop_be.set()
        for t in threads[4:]:
            t.join()

        st = fleet.stats()
        snap = router.stats()
        classes = snap.get("classes", {})
        p99 = float(np.percentile(inter["lat_ms"], 99)) \
            if inter["lat_ms"] else None
        backfill_ups = [e for e in st["events"]
                        if e["action"] == "scale_up"
                        and "backfill" in str(e.get("reason"))]
        checks.update(
            host_declared_dead=(st["hosts_lost"] == 1
                                and st["hosts"]["host-b"]["alive"]
                                is False),
            zero_lost_interactive=(not inter["errors"]
                                   and len(inter["lat_ms"]) == 1000),
            interactive_slo_held=(p99 is not None and p99 <= bound_ms),
            interactive_not_shed=(classes.get("interactive", {})
                                  .get("shed", 0) == 0),
            best_effort_shed_first=(be_shed[0] > 0),
            backfilled_to_target=(st["backfills"] >= 1
                                  and st["live_replicas"] == st["target"]
                                  and set(st["placement"].values())
                                  == {"host-a"}),
            backfill_zero_compiles=(bool(backfill_ups) and all(
                e.get("spinup_compiles") == 0 for e in backfill_ups)))
        detail = {
            "interactive_baseline_p99_ms": baseline_p99,
            "interactive_p99_ms": p99,
            "interactive_p99_bound_ms": round(bound_ms, 3),
            "interactive_completed": len(inter["lat_ms"]),
            "best_effort_completed": be_done[0],
            "best_effort_shed": be_shed[0],
            "backfill_latency_s": st["backfill_latency_s"],
            "fleet": {k: st[k] for k in
                      ("target", "live_replicas", "scale_ups",
                       "hosts_lost", "backfills", "placement")},
            "router": {k: snap.get(k) for k in
                       ("failovers", "replicas_lost",
                        "duplicates_suppressed")},
        }
        errs = inter["errors"][:5]
    finally:
        if fleet is not None:
            try:
                fleet.shutdown(drain=False, close_hosts=True)
            except Exception:
                pass
        for h in hosts:
            try:
                h.kill()
            except Exception:
                pass
        _f.clear()
    bools = [v for v in checks.values() if isinstance(v, bool)]
    result = {
        "schedule": "fleet-host-kill",
        "checks": checks,
        **detail,
        "errors": errs,
        "duration_s": round(time.time() - t0, 1),
        "passed": bool(bools) and all(bools),
    }
    if not quiet:
        print("chaos[fleet/host-kill]: passed=%s checks=%s (%.1fs)" %
              (result["passed"], checks, result["duration_s"]),
              file=sys.stderr)
    return result


def run_fleet(as_json=False, out_path=None):
    tmp = tempfile.mkdtemp(prefix="chaos-fleet-")
    try:
        runs = [run_fleet_schedule(tmp, quiet=as_json)]
    except Exception as exc:
        runs = [{"schedule": "fleet-host-kill", "passed": False,
                 "error": repr(exc)}]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    artifact = {
        "schedules": runs,
        "all_passed": all(r["passed"] for r in runs),
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    if as_json:
        print(json.dumps(artifact))
    else:
        print("chaos fleet: %d schedule(s), all_passed=%s -> %s" %
              (len(runs), artifact["all_passed"], out_path))
    return 0 if artifact["all_passed"] else 1


# -- decode schedules: continuous-batching LM serving under sabotage ----------
# two in-process `DecodeReplica`s (one shared cached-jit program space,
# so replica 2 must warm with ZERO compiles) behind a real
# ReplicaRouter; one replica SIGKILLed mid-decode.  The acceptance
# story: a decode request is REPLAYABLE (prompt + budget re-derive the
# lost KV state via prefill on a survivor), so zero admitted sequences
# are lost, none is delivered twice, and the steady state never
# presents XLA a novel shape.

def _decode_cfg():
    from incubator_mxnet_tpu.llm import LMConfig
    return LMConfig(vocab_size=48, num_layers=2, num_heads=2, hidden=16,
                    ffn_mult=2, max_len=32, eos_id=0)


def _decode_params(cfg, seed=0):
    import numpy as np
    rng = np.random.default_rng(seed)
    c, f = cfg.hidden, cfg.hidden * cfg.ffn_mult
    mk = lambda *s: rng.standard_normal(s).astype(np.float32) * 0.1  # noqa: E731
    p = {"lm_embed_weight": mk(cfg.vocab_size, c),
         "lm_final_ln_gamma": np.ones((c,), np.float32),
         "lm_final_ln_beta": np.zeros((c,), np.float32)}
    for i in range(cfg.num_layers):
        pre = "lm_block%d_" % i
        for suffix, shape in (("ln1_gamma", (c,)), ("ln1_beta", (c,)),
                              ("qkv_weight", (3 * c, c)),
                              ("qkv_bias", (3 * c,)),
                              ("out_proj_weight", (c, c)),
                              ("out_proj_bias", (c,)),
                              ("ln2_gamma", (c,)), ("ln2_beta", (c,)),
                              ("fc1_weight", (f, c)), ("fc1_bias", (f,)),
                              ("fc2_weight", (c, f)), ("fc2_bias", (c,))):
            p[pre + suffix] = np.ones(shape, np.float32) \
                if suffix.endswith("gamma") else (
                mk(*shape) if "weight" in suffix
                else np.zeros(shape, np.float32))
    return p


def _drive_decode(router, rng_seed, n_threads=4, per=20, kill_at=None,
                  kill_fn=None):
    """Closed-loop mixed-length decode traffic with caller-owned
    request ids; optionally fire `kill_fn` after `kill_at` admissions.
    Returns (ok results, errors, submitted rids)."""
    import numpy as np
    rng = np.random.default_rng(rng_seed)
    prompts = [[int(t) for t in rng.integers(1, 40, int(n))]
               for n in rng.choice([2, 3, 5, 7, 8], n_threads * per)]
    results, errors, rids = [], [], []
    accepted = [0]
    fired = [False]
    lock = threading.Lock()

    def client(tid):
        for j in range(per):
            idx = tid * per + j
            rid = "dec-%d" % idx
            try:
                f = router.submit(
                    {"tokens": prompts[idx],
                     "max_new_tokens": 4 + idx % 5},
                    timeout_ms=60000,
                    priority=("interactive", "batch",
                              "best_effort")[idx % 3],
                    request_id=rid)
                with lock:
                    rids.append(rid)
                    accepted[0] += 1
                    if kill_at is not None and accepted[0] == kill_at \
                            and not fired[0]:
                        fired[0] = True
                        kill_fn()
                results.append(f.result(120))
            except Exception as exc:   # a lost admitted request = FINDING
                errors.append(repr(exc))

    threads = [threading.Thread(target=client, args=(i,),
                                name="mx-chaos-decode-client-%d" % i)
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results, errors, rids


def run_decode_schedule(name, quiet=False):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import analysis
    from incubator_mxnet_tpu.resilience import faults as _f
    t0 = time.time()
    checks = {}
    errs = []
    _f.configure("seed=51")   # trace/log only; the kill is real
    analysis.recompile.reset()
    cfg = _decode_cfg()
    reps = [mx.serving.DecodeReplica(
        cfg, _decode_params(cfg), replica_id="dec%d" % i,
        slots=4, buckets=(4, 8)) for i in range(2)]
    router = mx.serving.ReplicaRouter(reps, name="chaos-decode",
                                      health_interval_s=0.1,
                                      max_dispatches=4)
    try:
        # replica 2 warms off replica 1's live programs: same graph
        # keys through one cached-jit space, so spinup is compile-free
        checks["spinup_zero_compiles"] = \
            reps[1].ready_info.get("compiles") == 0
        base_compiles = [r.engine.programs.compile_count() for r in reps]
        if name == "decode-replica-kill":
            results, errors, rids = _drive_decode(
                router, rng_seed=51, kill_at=30, kill_fn=reps[0].kill)
            st = router.stats()
            survivors = [r for r in reps if r.replica_id != "dec0"]
            executed = [rid for r in survivors
                        for rid in r.engine.stats()["executed_rids"]]
            answered = {r["rid"] for r in results if isinstance(r, dict)}
            checks.update(
                zero_lost=(len(results) == len(rids) == 80
                           and not errors),
                every_sequence_generated=(all(
                    isinstance(r, dict) and r["tokens"]
                    for r in results)),
                zero_duplicate_execution=(
                    len(executed) == len(set(executed))
                    and st["duplicates_suppressed"] == 0),
                replica_declared_dead=(st["replicas_lost"] >= 1),
                every_rid_delivered_once=(len(answered) == 80),
                failovers=st["failovers"])
            errs = errors[:5]
        elif name == "decode-steady-state":
            results, errors, rids = _drive_decode(router, rng_seed=52)
            after = [r.engine.programs.compile_count() for r in reps]
            churn = [f for f in analysis.recompile.findings()
                     if str(f.get("key", "")).startswith("decode:")]
            checks.update(
                zero_lost=(len(results) == 80 and not errors),
                zero_steady_state_compiles=(after == base_compiles),
                zero_recompile_findings=(not churn),
                programs_stable=(all(
                    r.engine.programs.program_count() == 3
                    for r in reps)))
            errs = errors[:5]
        else:
            raise ValueError("unknown decode schedule %r" % name)
    finally:
        try:
            router.shutdown(drain=False)
        except Exception:
            pass
        for r in reps:
            try:
                r.kill()
            except Exception:
                pass
        _f.clear()
    bools = [v for v in checks.values() if isinstance(v, bool)]
    result = {
        "schedule": name,
        "checks": checks,
        "errors": errs,
        "duration_s": round(time.time() - t0, 1),
        "passed": bool(bools) and all(bools),
    }
    if not quiet:
        print("chaos[decode/%s]: passed=%s checks=%s (%.1fs)" %
              (name, result["passed"], checks, result["duration_s"]),
              file=sys.stderr)
    return result


def run_decode(as_json=False, out_path=None):
    runs = []
    for name in ("decode-steady-state", "decode-replica-kill"):
        try:
            runs.append(run_decode_schedule(name, quiet=as_json))
        except Exception as exc:
            runs.append({"schedule": name, "passed": False,
                         "error": repr(exc)})
    artifact = {
        "schedules": runs,
        "all_passed": all(r["passed"] for r in runs),
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    if as_json:
        print(json.dumps(artifact))
    else:
        print("chaos decode: %d schedule(s), all_passed=%s -> %s" %
              (len(runs), artifact["all_passed"], out_path))
    return 0 if artifact["all_passed"] else 1


# -- training-guardian schedules: silent-failure recovery ---------------------
# in-process seeded schedules over small Module.fit runs; every recovery
# path is certified with zero unified-program-cache compiles

def _train_model():
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import sym
    np.random.seed(0)
    mx.random.seed(0)
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(net, name="softmax")
    return mx.mod.Module(net, context=mx.cpu())


def _train_iter(n=128, bs=8):
    import numpy as np
    from incubator_mxnet_tpu import io
    rng = np.random.RandomState(3)
    x = rng.standard_normal((n, 10)).astype("float32")
    y = rng.randint(0, 4, n).astype("float32")
    return io.NDArrayIter(x, y, batch_size=bs, shuffle=False)


def _train_fit(mod, ckpt_dir=None):
    import incubator_mxnet_tpu as mx
    mod.fit(_train_iter(), num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05}, eval_metric="acc",
            initializer=mx.initializer.Xavier(),
            checkpoint_dir=ckpt_dir, checkpoint_period=4)


def _params_sha(mod):
    import hashlib
    args, auxs = mod.get_params()
    h = hashlib.sha256()
    for k in sorted(args):
        h.update(args[k].asnumpy().tobytes())
    for k in sorted(auxs):
        h.update(auxs[k].asnumpy().tobytes())
    return h.hexdigest()


def run_train_schedule(name, tmp, quiet=False):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import compile as _compile
    from incubator_mxnet_tpu.resilience import faults as _f
    t0 = time.time()
    checks = {}
    os.environ["MXNET_GUARDIAN_INTERVAL"] = "4"
    os.environ["MXNET_GUARDIAN_SPIKE_WINDOW"] = "4"

    def compiles():
        return _compile.stats()["counters"]["compiles"]

    if name == "warmup":
        # pays the process's cold compiles so every REAL schedule can
        # gate on zero-compile recovery (the live tier serves rebuilt
        # programs); also the fault-free baseline sha.  The K-step scan
        # AND the 1-step program both warm here — a post-rollback resume
        # trains partial blocks (the quarantined window breaks block
        # collection), so recovery dispatches the 1-step program too.
        _f.clear()
        mod = _train_model()
        _train_fit(mod)
        prev = os.environ.get("MXNET_FUSED_STEP_BLOCK")
        os.environ["MXNET_FUSED_STEP_BLOCK"] = "1"
        try:
            _train_fit(_train_model())
        finally:
            if prev is None:
                os.environ.pop("MXNET_FUSED_STEP_BLOCK", None)
            else:
                os.environ["MXNET_FUSED_STEP_BLOCK"] = prev
        checks["completed"] = True
        checks["baseline_sha"] = _params_sha(mod)
        checks["guardian_active"] = mod._guardian is not None and \
            mod._guardian.stats()["steps_observed"] > 0
    elif name == "nonfinite-skip":
        # injected NaN gradient -> in-graph skip, deterministic
        # continuation: two identical seeded runs end bit-identical
        def one_run():
            _f.configure("seed=31;grad.nonfinite:error(at=5)")
            mod = _train_model()
            c0 = compiles()
            _train_fit(mod)
            st = mod._guardian.stats()
            _f.clear()
            return _params_sha(mod), st, compiles() - c0
        sha1, st1, d1 = one_run()
        sha2, st2, d2 = one_run()
        checks.update(
            skip_fired=(st1["skips"] == 1 and st1["injected_nonfinite"] == 1),
            batch_quarantined=(st1["quarantined"] == 1),
            deterministic_continuation=(sha1 == sha2),
            zero_recovery_compiles=(d1 == 0 and d2 == 0))
    elif name == "spike-rollback":
        # injected loss spike -> rollback-to-last-good; final params
        # bit-identical to a clean reference that skipped the same
        # quarantined window from the same healthy checkpoint state
        ck_a = os.path.join(tmp, "ck-spike")
        ck_b = os.path.join(tmp, "ck-ref")
        _f.configure("seed=32;loss.spike:error(at=10)")
        mod = _train_model()
        c0 = compiles()
        _train_fit(mod, ck_a)
        st = mod._guardian.stats()
        sha_rb = _params_sha(mod)
        d_rb = compiles() - c0
        _f.clear()
        os.makedirs(ck_b, exist_ok=True)
        shutil.copyfile(os.path.join(ck_a, "quarantine.jsonl"),
                        os.path.join(ck_b, "quarantine.jsonl"))
        ref = _train_model()
        c1 = compiles()
        _train_fit(ref, ck_b)
        checks.update(
            rollback_fired=(st["rollbacks"] == 1 and st["spikes"] == 1),
            window_quarantined=(st["quarantined"] >= 1),
            bit_identical_vs_clean=(sha_rb == _params_sha(ref)),
            zero_recovery_compiles=(d_rb == 0 and compiles() - c1 == 0))
    elif name == "corrupt-record":
        # injected record corruption -> substituted + counted +
        # quarantined; a resumed iterator skips the record entirely
        import numpy as np
        import cv2
        from incubator_mxnet_tpu import recordio
        from incubator_mxnet_tpu.image import ImageRecordIterImpl
        from incubator_mxnet_tpu.resilience.guardian import QuarantineLog
        rec = os.path.join(tmp, "c.rec")
        rng = np.random.RandomState(0)
        w = recordio.MXRecordIO(rec, "w")
        for i in range(24):
            ok, enc = cv2.imencode(
                ".png", rng.randint(0, 255, (40, 40, 3), dtype=np.uint8))
            w.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                                  enc.tobytes()))
        w.close()
        qlog = QuarantineLog(os.path.join(tmp, "quarantine.jsonl"))
        # record= targeting: hit-count (at=) ordering is thread-schedule
        # dependent under the multi-threaded batch builders
        _f.configure("seed=33;io.corrupt_record:corrupt(record=6)")
        it = ImageRecordIterImpl(path_imgrec=rec, data_shape=(3, 32, 32),
                                 batch_size=4, preprocess_threads=2)
        it.set_quarantine(qlog)
        n1 = sum(b.data[0].shape[0] - b.pad for b in it)
        corrupt_first = it.corrupt_records
        it.close()
        _f.clear()
        entries = qlog.load()
        # "resume": a fresh iterator with the quarantine applied never
        # reads the poisoned record again (no fault clause configured)
        it2 = ImageRecordIterImpl(path_imgrec=rec, data_shape=(3, 32, 32),
                                  batch_size=4, preprocess_threads=2)
        it2.apply_quarantine(entries)
        labels = []
        for b in it2:
            labels.extend(
                b.label[0].asnumpy()[:b.data[0].shape[0] - b.pad].tolist())
        it2.close()
        bad = {int(e["record"]) for e in entries
               if e.get("record") is not None}
        checks.update(
            corrupt_detected=(corrupt_first == 1 and n1 == 24),
            quarantine_logged=(bad == {6}),
            skipped_on_resume=(it2.corrupt_records == 0 and
                               len(labels) == 23 and
                               not any(float(r) in labels for r in bad)))
    else:
        raise ValueError("unknown train schedule %r" % name)
    bools = [v for v in checks.values() if isinstance(v, bool)]
    result = {
        "schedule": name,
        "checks": {k: v for k, v in checks.items() if k != "baseline_sha"},
        "duration_s": round(time.time() - t0, 1),
        "passed": bool(bools) and all(bools),
    }
    if not quiet:
        print("chaos[train/%s]: passed=%s checks=%s (%.1fs)" %
              (name, result["passed"], result["checks"],
               result["duration_s"]), file=sys.stderr)
    return result


def run_train(as_json=False, out_path=None):
    runs = []
    for name in ("warmup", "nonfinite-skip", "spike-rollback",
                 "corrupt-record"):
        tmp = tempfile.mkdtemp(prefix="chaos-train-%s-" % name)
        try:
            runs.append(run_train_schedule(name, tmp, quiet=as_json))
        except Exception as exc:
            runs.append({"schedule": name, "passed": False,
                         "error": repr(exc)})
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    artifact = {
        "schedules": runs,
        "all_passed": all(r["passed"] for r in runs),
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    if as_json:
        print(json.dumps(artifact))
    else:
        print("chaos train: %d schedule(s), all_passed=%s -> %s" %
              (len(runs), artifact["all_passed"], out_path))
    return 0 if artifact["all_passed"] else 1


# -- sharded-embedding chaos: SIGKILL a row shard mid-traffic -----------------
#
# The mxembed failure matrix (embedding/sharded.py): a shard server dying
# becomes a structured ServerLostError naming the shard and its rows;
# training recovers by restoring the checkpointed table and replaying
# from the checkpoint (bit-identical, since the lazy updates are
# deterministic); serving recovers through the on_shard_lost hook
# (respawn + replace_shard) with ZERO lost admitted requests.

def _spawn_shard_proc(port):
    """One embedding row-shard server as a real subprocess, so the
    schedule can SIGKILL it (not a polite in-process shutdown)."""
    env = dict(os.environ,
               DMLC_PS_ROOT_URI="127.0.0.1", DMLC_PS_ROOT_PORT=str(port),
               DMLC_NUM_WORKER="1", JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    env.pop("MXNET_FAULTS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "incubator_mxnet_tpu.dist.server"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        cwd=REPO)
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), 0.5).close()
            return proc
        except OSError:
            if proc.poll() is not None:
                break
            time.sleep(0.05)
    proc.kill()
    raise RuntimeError("embedding shard server on port %d never came up"
                       % port)


def _table_sha(table):
    import hashlib
    return hashlib.sha256(table.checkpoint_rows().tobytes()).hexdigest()


def _embed_fit_model(rows, dim, table, n=96, bs=16, seed=0):
    """The wide-and-deep fixture: deterministic id stream + tower,
    bound with inputs_need_grad so fit's classic loop exposes the
    embedding gradient (examples/recommender/wide_deep.py)."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import embedding as mxembed, io, sym
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, rows, size=(n, 2)).astype("int64")
    dense = rng.standard_normal((n, 4)).astype("float32")
    label = ((ids[:, 0] + ids[:, 1]) % 2).astype("float32")
    base = io.NDArrayIter({"emb": ids.astype("float32"), "dense": dense},
                          {"softmax_label": label}, batch_size=bs)
    adapter = mxembed.EmbeddingFitAdapter(table, base, id_field=0)
    emb = sym.Variable("emb")
    den = sym.Variable("dense")
    deep = sym.FullyConnected(emb, num_hidden=8, name="deep1")
    deep = sym.Activation(deep, act_type="relu")
    wide = sym.FullyConnected(den, num_hidden=8, name="wide1")
    out = sym.FullyConnected(deep + wide, num_hidden=2, name="head")
    net = sym.SoftmaxOutput(out, name="softmax")
    np.random.seed(seed)
    mx.random.seed(seed)
    mod = mx.mod.Module(net, data_names=("emb", "dense"),
                        label_names=("softmax_label",), context=mx.cpu())
    mod.bind(data_shapes=adapter.provide_data,
             label_shapes=adapter.provide_label,
             for_training=True, inputs_need_grad=True)
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian"))
    return mod, adapter


def _embed_fit_epoch(mod, adapter):
    import incubator_mxnet_tpu as mx
    mod.fit(adapter, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            batch_end_callback=adapter.make_callback(mod),
            eval_metric="acc")


def run_embedding_schedule(name, quiet=False):
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import embedding as mxembed
    from incubator_mxnet_tpu.resilience import ServerLostError
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # fast shard-death diagnosis (prod defaults wait seconds/reconnect)
    os.environ["MXNET_PS_RECONNECT_WAIT"] = "0.1"
    os.environ["MXNET_PS_MAX_RETRIES"] = "2"
    os.environ["MXNET_EMBED_BREAKER_THRESHOLD"] = "2"
    t0 = time.time()
    checks = {}
    rows, dim = 64, 4
    seed = 23

    if name == "train-shard-kill":
        # clean reference: epoch 1, checkpoint, epoch 2 -> final shas
        def fresh(ports):
            table = mxembed.ShardedEmbedding(
                "chaos_wd", rows, dim,
                [("127.0.0.1", p) for p in ports], seed=seed,
                cache_rows=32,
                optimizer=mx.optimizer.SGD(learning_rate=0.1,
                                           momentum=0.0))
            mod, adapter = _embed_fit_model(rows, dim, table, seed=seed)
            return table, mod, adapter

        ports = [_free_port(), _free_port()]
        procs = [_spawn_shard_proc(p) for p in ports]
        try:
            table, mod, adapter = fresh(ports)
            _embed_fit_epoch(mod, adapter)
            ck_table = table.checkpoint_rows()
            ck_args, ck_auxs = mod.get_params()
            ck_args = {k: v.asnumpy().copy() for k, v in ck_args.items()}
            _embed_fit_epoch(mod, adapter)
            ref_table_sha, ref_dense_sha = _table_sha(table), \
                _params_sha(mod)

            # chaos lane: restore epoch-1 state, then SIGKILL shard 1
            # at a seeded batch boundary inside the replayed epoch 2
            table.restore_rows(ck_table)
            mod.set_params({k: mx.nd.array(v)
                            for k, v in ck_args.items()}, ck_auxs,
                           allow_missing=False, force_init=True)
            kill_at = int(np.random.RandomState(seed).randint(1, 4))
            state = {"batches": 0, "err": None}
            push_cb = adapter.make_callback(mod)

            def chaos_cb(param):
                push_cb(param)
                state["batches"] += 1
                if state["batches"] == kill_at:
                    procs[1].kill()          # SIGKILL, mid-traffic
                    procs[1].wait()
            try:
                mod.fit(adapter, num_epoch=1, optimizer="sgd",
                        optimizer_params={"learning_rate": 0.1},
                        batch_end_callback=chaos_cb, eval_metric="acc")
            except ServerLostError as e:
                state["err"] = e
            err = state["err"]
            checks["server_lost_structured"] = (
                err is not None and err.server == 1
                and any("chaos_wd" in k for k in err.keys))
            checks["killed_sigkill"] = procs[1].returncode == -9
            # auto-resume: respawn the shard, restore the checkpointed
            # table and dense params, replay the epoch from the
            # checkpoint — bit-identical to the clean reference
            ports[1] = _free_port()
            procs[1] = _spawn_shard_proc(ports[1])
            table.replace_shard(1, "127.0.0.1", ports[1],
                                restore=ck_table)
            table.restore_rows(ck_table)
            mod.set_params({k: mx.nd.array(v)
                            for k, v in ck_args.items()}, ck_auxs,
                           allow_missing=False, force_init=True)
            adapter.reset()      # the aborted epoch left it mid-stream
            _embed_fit_epoch(mod, adapter)
            checks["resumed_table_bit_identical"] = (
                _table_sha(table) == ref_table_sha)
            checks["resumed_dense_bit_identical"] = (
                _params_sha(mod) == ref_dense_sha)
            checks["failover_counted"] = table.stats()["failovers"] == 1
            table.close()
        finally:
            for p in procs:
                p.kill()
                p.communicate()

    elif name == "serve-shard-kill":
        from incubator_mxnet_tpu import io, sym
        from incubator_mxnet_tpu.serving import LocalReplica, ReplicaRouter
        ports = [_free_port(), _free_port()]
        procs = [_spawn_shard_proc(p) for p in ports]
        try:
            table = mxembed.ShardedEmbedding(
                "chaos_serve", rows, dim,
                [("127.0.0.1", p) for p in ports], seed=seed,
                cache_rows=0)        # every lookup exercises the wire
            ck = table.checkpoint_rows()
            np.random.seed(seed)
            mx.random.seed(seed)
            net = sym.FullyConnected(sym.Variable("emb"), num_hidden=3,
                                     name="head")
            net = sym.SoftmaxOutput(net, name="softmax")
            mod = mx.mod.Module(net, data_names=("emb",),
                                label_names=("softmax_label",),
                                context=mx.cpu())
            mod.bind(data_shapes=[io.DataDesc("emb", (2, 2 * dim))],
                     label_shapes=[io.DataDesc("softmax_label", (2,))],
                     for_training=False, grad_req="null")
            mod.init_params(mx.initializer.Xavier())
            args, auxs = mod.get_params()
            reps = [LocalReplica(
                mx.serving.ServedModel(
                    net, args, auxs, data_shapes=[("emb", (1, 2 * dim))],
                    buckets=(1, 2, 4), ctx=mx.cpu(), name="tower"),
                replica_id="r%d" % i) for i in range(2)]
            lock = threading.Lock()
            state = {"done": 0, "ok": 0, "killed": False, "gen": 0}

            def on_shard_lost(err):
                # thread-safe respawn: first caller replaces the shard,
                # racers see the bumped generation and just retry
                with lock:
                    gen = state["gen"]
                    if gen == table.failovers:
                        port = _free_port()
                        procs.append(_spawn_shard_proc(port))
                        table.replace_shard(err.server, "127.0.0.1",
                                            port, restore=ck)
                        state["gen"] = table.failovers
                return True

            rng = np.random.RandomState(seed)
            reqs = rng.randint(0, rows, size=(60, 2, 2))
            kill_after = int(rng.randint(8, 16))
            with ReplicaRouter(reps, health_interval_s=0.2) as router:
                path = mxembed.EmbeddingServingPath(
                    table, router, embed_input="emb",
                    on_shard_lost=on_shard_lost)
                baseline = {}
                for i, ids in enumerate(reqs):
                    baseline[i] = path.predict(
                        ids, timeout_ms=10000)[0].asnumpy()
                n_before = path.requests

                def worker(idx0):
                    for i in range(idx0, len(reqs), 4):
                        got = path.predict(reqs[i],
                                           timeout_ms=10000)[0].asnumpy()
                        with lock:
                            state["done"] += 1
                            if np.allclose(got, baseline[i]):
                                state["ok"] += 1
                        if not state["killed"] and \
                                state["done"] >= kill_after:
                            with lock:
                                if not state["killed"]:
                                    state["killed"] = True
                                    procs[0].kill()   # SIGKILL shard 0
                                    procs[0].wait()
                threads = [threading.Thread(target=worker, args=(k,))
                           for k in range(4)]
                for th in threads:
                    th.start()
                for th in threads:
                    th.join()
            st = path.stats()
            checks["killed_sigkill"] = procs[0].returncode == -9
            checks["zero_lost_admitted"] = (
                state["done"] == len(reqs)
                and st["completed"] == n_before + len(reqs))
            checks["results_match_baseline"] = state["ok"] == len(reqs)
            checks["failover_fired"] = (st["shard_failovers"] >= 1
                                        and table.stats()["failovers"] >= 1)
            table.close()
        finally:
            for p in procs:
                p.kill()
                p.communicate()
    else:
        raise ValueError("unknown embedding schedule %r" % name)

    bools = [v for v in checks.values() if isinstance(v, bool)]
    result = {"schedule": name, "seed": seed, "checks": checks,
              "duration_s": round(time.time() - t0, 1),
              "passed": bool(bools) and all(bools)}
    if not quiet:
        print("chaos[embed/%s]: passed=%s checks=%s (%.1fs)" %
              (name, result["passed"], result["checks"],
               result["duration_s"]), file=sys.stderr)
    return result


def run_embedding(as_json=False, out_path=None):
    runs = []
    for name in ("train-shard-kill", "serve-shard-kill"):
        try:
            runs.append(run_embedding_schedule(name, quiet=as_json))
        except Exception as exc:
            runs.append({"schedule": name, "passed": False,
                         "error": repr(exc)})
    artifact = {"schedules": runs,
                "all_passed": all(r["passed"] for r in runs)}
    if out_path:
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    if as_json:
        print(json.dumps(artifact))
    else:
        print("chaos embedding: %d schedule(s), all_passed=%s -> %s" %
              (len(runs), artifact["all_passed"], out_path))
    return 0 if artifact["all_passed"] else 1


# -- train-to-serve loop schedules: the continuous-training hand-off ----------
#
# A REAL trainer process (tools/loop_trainer.py) publishes guardian-
# healthy elastic checkpoints into a shared ModelRegistry while a
# 2-replica remote fleet promotes them through the LoopController's
# canary gate under live traffic.  The failure matrix: a corrupted
# training shard + loss spike (guardian rollback -> registry fence; the
# fleet never serves a fenced or rejected version, zero admitted
# requests lost, zero swap compiles, next clean version inside the
# freshness SLO), a healthy-stamped-but-poisoned publish (the serving-
# side canary rejects it, swaps the canary replica back, stamps the
# version rejected — durable, never retried), and a torn publish (the
# truncated manifest is invisible to the watcher; the incumbent keeps
# serving; a clean re-publish promotes).

def _loop_elastic_ckpt(tmp, name, args, auxs, step, transform=None):
    """Params exported as ONE guardian-healthy elastic checkpoint dir."""
    import incubator_mxnet_tpu as mx
    root = os.path.join(tmp, name)
    arrays = {}
    for k, v in args.items():
        a = v.asnumpy()
        arrays["arg:" + k] = transform(k, a) if transform else a
    for k, v in auxs.items():
        arrays["aux:" + k] = v.asnumpy()
    mgr = mx.checkpoint.CheckpointManager(root, async_snapshots=False)
    mgr.snapshot(arrays=arrays, step=step, epoch=0, nbatch=step,
                 meta={"health": {"status": "healthy"}}, sync=True)
    mgr.close()
    return os.path.join(root, "ckpt-%010d" % step)


def _loop_boot_labels(args, x):
    """The boot model's own argmax on `x` — a holdout on which the
    incumbent scores exactly 1.0, so a same-params candidate ties and a
    head-negated (poisoned) one scores ~0."""
    import numpy as np
    w0 = args["fc0_weight"].asnumpy()
    b0 = args["fc0_bias"].asnumpy()
    wh = args["head_weight"].asnumpy()
    bh = args["head_bias"].asnumpy()
    h = np.tanh(x @ w0.T + b0)
    return (h @ wh.T + bh).argmax(axis=1).astype(np.float32)


def _loop_traffic(router, stop_evt, n_threads=3):
    """Open-ended closed-loop traffic until `stop_evt`; returns
    (threads, ok_counter, errors) — the caller starts and joins."""
    import numpy as np
    x = np.random.default_rng(9).standard_normal((2, 16)).astype(
        np.float32)
    oks, errors = [0], []
    lock = threading.Lock()

    def client():
        while not stop_evt.is_set():
            try:
                f = router.submit({"data": x}, timeout_ms=30000)
                f.result(60)
                with lock:
                    oks[0] += 1
            except Exception as exc:   # a lost request is the FINDING
                errors.append(repr(exc))

    threads = [threading.Thread(target=client,
                                name=f"mx-chaos-loop-client-{i}")
               for i in range(n_threads)]
    return threads, oks, errors


def run_loop_schedule(name, tmp, quiet=False):
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import loop as mxloop
    from incubator_mxnet_tpu.checkpoint import manifest as _ck_manifest
    from incubator_mxnet_tpu.resilience import faults as _f
    from incubator_mxnet_tpu.resilience.faults import TornWrite
    t0 = time.time()
    checks = {}
    errs = []
    # the loop schedules certify the canary gate, not eviction timing —
    # a generous liveness deadline keeps a CPU-starved worker (trainer
    # subprocess + fleet sharing one loaded box) from being falsely
    # declared lost mid-canary
    router, reps, (mod, prefix) = _serving_fleet(tmp, n=2,
                                                 health_deadline_s=15.0)
    args, auxs = mod.get_params()
    boot_ck = _loop_elastic_ckpt(tmp, "boot", args, auxs, step=0)
    reg = mxloop.ModelRegistry(os.path.join(tmp, "registry"))

    def publish(ckpt, step):
        return reg.publish(ckpt, step=step,
                           health={"status": "healthy"},
                           watermark={"step": step, "time": time.time()})

    stop = threading.Event()
    threads, oks, errors = _loop_traffic(router, stop)
    try:
        checks["spinup_zero_compiles"] = all(
            r.ready_info.get("compiles") == 0 for r in reps[1:])
        base = [r.stats() for r in reps]
        for t in threads:
            t.start()
        if name == "poisoned-shard-loop":
            # the real loop: trainer subprocess reads a record shard
            # through MXRecordIO with a seeded payload corruption AND an
            # injected loss spike; the guardian rolls back, the
            # publisher fences the disowned window, and the serving
            # side keeps promoting only clean versions
            _f.configure("seed=70")   # driver side: trace only
            sys.path.insert(0, os.path.join(REPO, "tools"))
            import loop_trainer as _lt
            ctl = mxloop.LoopController(
                router, reg, _lt.holdout_batch(), canary_tol=1.0,
                poll_interval_s=0.2, freshness_slo_s=120.0,
                incumbent_checkpoint=boot_ck)
            report_path = os.path.join(tmp, "trainer_report.json")
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PYTHONPATH=REPO + os.pathsep
                       + os.environ.get("PYTHONPATH", ""),
                       MXNET_FAULTS=("seed=71;"
                                     "io.corrupt_record:corrupt(at=40);"
                                     "loss.spike:error(at=30)"),
                       MXNET_GUARDIAN_INTERVAL="4",
                       MXNET_GUARDIAN_SPIKE_WINDOW="4")
            env.pop("MXNET_FAULTS_LOG", None)
            proc = subprocess.Popen(
                [sys.executable,
                 os.path.join(REPO, "tools", "loop_trainer.py"),
                 "--registry", reg.root,
                 "--ckpt", os.path.join(tmp, "trainer-ck"),
                 "--rec", os.path.join(tmp, "shard.rec"),
                 "--report", report_path, "--write-shard", "96"],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            promoted = []
            rejected = []
            deadline = time.time() + 300
            quiet_polls = 0
            try:
                while time.time() < deadline:
                    try:
                        status = ctl.poll_once()
                    except mxloop.CanaryRejectedError as exc:
                        rejected.append(exc.version)
                        continue
                    if status.get("status") == "promoted":
                        promoted.append(status)
                        quiet_polls = 0
                    elif proc.poll() is not None:
                        quiet_polls += 1
                        if quiet_polls >= 5:
                            break
                    time.sleep(0.25)
            finally:
                try:
                    proc.communicate(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.communicate()
            with open(report_path) as f:
                report = json.load(f)
            st = ctl.stats()
            checks.update(
                trainer_completed=bool(report.get("completed")),
                corrupt_record_detected=(
                    report.get("corrupt_records", 0) >= 1),
                guardian_rolled_back=(
                    (report.get("guardian") or {}).get("rollbacks", 0)
                    >= 1),
                registry_fenced=(len(report.get("fences") or ()) >= 1),
                clean_versions_promoted=(len(promoted) >= 1),
                poisoned_never_served=(
                    not rejected and st["canary_rejections"] == 0
                    and all(not reg.fenced(p["version"])
                            and reg.rejected(p["version"]) is None
                            for p in promoted)),
                freshness_within_slo=(st.get("freshness_slo_met") == 1),
                promoted_versions=[p["version"] for p in promoted],
                fenced_windows=report.get("fences"))
        elif name == "poisoned-publish-canary":
            # a healthy-stamped checkpoint with sabotaged weights lands
            # in the registry (poisoned data slipped past the trainer):
            # the serving-side canary is the LAST line of defense
            _f.configure("seed=72")
            x = np.random.default_rng(7).standard_normal(
                (4, 16)).astype(np.float32)
            labels = _loop_boot_labels(args, x)
            ctl = mxloop.LoopController(
                router, reg, ({"data": x}, labels), canary_tol=0.05,
                poll_interval_s=0.2, freshness_slo_s=120.0,
                incumbent_checkpoint=boot_ck)
            good_ck = _loop_elastic_ckpt(tmp, "good", args, auxs, 1)
            poison_ck = _loop_elastic_ckpt(
                tmp, "poison", args, auxs, 2,
                transform=lambda k, a: -a if k == "head_weight" else a)
            publish(good_ck, 1)
            st1 = ctl.poll_once()
            checks["clean_version_promoted"] = (
                st1.get("status") == "promoted" and st1["version"] == 1)
            publish(poison_ck, 2)
            rejected_exc = None
            try:
                ctl.poll_once()
            except mxloop.CanaryRejectedError as exc:
                rejected_exc = exc
            checks["canary_rejected_structured"] = (
                rejected_exc is not None and rejected_exc.version == 2
                and rejected_exc.canary_score
                < rejected_exc.incumbent_score)
            # the canary replica was swapped BACK: every replica still
            # classifies the holdout exactly like the incumbent
            outs = [r.submit({"data": x}, timeout_ms=30000).result(60)
                    for r in reps]
            checks["fleet_swapped_back"] = all(
                bool((np.asarray(o[0]).argmax(axis=1) == labels).all())
                for o in outs)
            checks["rejection_stamp_durable"] = (
                reg.rejected(2) is not None
                and _ck_manifest.is_rejected(poison_ck)
                and mxloop.ModelRegistry(
                    reg.root).latest()["version"] == 1)
            st2 = ctl.poll_once()
            checks["never_retried"] = (
                st2.get("status") == "idle"
                and ctl.stats()["canary_rejections"] == 1)
        elif name == "torn-publish":
            # the publisher dies mid-commit: the truncated manifest
            # must be invisible, the fleet keeps serving, and a clean
            # re-publish of the same step promotes normally
            x = np.random.default_rng(7).standard_normal(
                (4, 16)).astype(np.float32)
            labels = _loop_boot_labels(args, x)
            ctl = mxloop.LoopController(
                router, reg, ({"data": x}, labels), canary_tol=0.05,
                poll_interval_s=0.2, freshness_slo_s=120.0,
                incumbent_checkpoint=boot_ck)
            good_ck = _loop_elastic_ckpt(tmp, "good", args, auxs, 1)
            v2_ck = _loop_elastic_ckpt(tmp, "v2", args, auxs, 2)
            publish(good_ck, 1)
            checks["clean_version_promoted"] = (
                ctl.poll_once().get("status") == "promoted")
            _f.configure("seed=73;publish.commit:torn(at=1)")
            torn_raised = False
            try:
                publish(v2_ck, 2)
            except TornWrite:
                torn_raised = True
            _f.configure("seed=73")
            torn_path = os.path.join(reg.root, "v-0000000002.json")
            checks["torn_publish_raised"] = torn_raised
            checks["torn_manifest_invisible"] = (
                os.path.exists(torn_path)
                and reg.latest()["version"] == 1
                and ctl.poll_once().get("status") == "idle"
                and reg.stats()["torn_manifests"] == 1)
            out = router.predict({"data": x}, timeout_ms=30000)
            checks["fleet_kept_serving"] = bool(
                (np.asarray(out[0]).argmax(axis=1) == labels).all())
            publish(v2_ck, 2)   # clean re-publish commits atomically
            st2 = ctl.poll_once()
            checks["clean_republish_promoted"] = (
                st2.get("status") == "promoted" and st2["version"] == 2)
        else:
            raise ValueError("unknown loop schedule %r" % name)
        stop.set()
        for t in threads:
            t.join(30)
        after = [r.stats() for r in reps]
        compiles = [
            (s.get("cache") or {}).get("compiles", 0) -
            (b.get("cache") or {}).get("compiles", 0)
            for b, s in zip(base, after)]
        checks.update(
            zero_lost=(oks[0] > 0 and not errors),
            zero_swap_compiles=all(c == 0 for c in compiles),
            requests_served=oks[0])
        errs = errors[:5] if errors else []
    finally:
        stop.set()
        try:
            router.shutdown(drain=False)
        except Exception:
            pass
        for r in reps:
            try:
                r.kill()
            except Exception:
                pass
        _f.clear()
    bools = [v for v in checks.values() if isinstance(v, bool)]
    result = {
        "schedule": name,
        "checks": checks,
        "errors": errs,
        "duration_s": round(time.time() - t0, 1),
        "passed": bool(bools) and all(bools),
    }
    if not quiet:
        print("chaos[loop/%s]: passed=%s checks=%s (%.1fs)" %
              (name, result["passed"], checks, result["duration_s"]),
              file=sys.stderr)
    return result


def run_loop(as_json=False, out_path=None):
    runs = []
    for name in ("poisoned-shard-loop", "poisoned-publish-canary",
                 "torn-publish"):
        # one retry on an ESCAPED exception only: on an oversubscribed
        # box (this suite runs trainer + 2 workers + driver on shared
        # cores) a starved worker can be declared lost mid-schedule —
        # an infra artifact, not the invariant under test.  A schedule
        # that RAN but failed its checks is never retried.
        for attempt in (1, 2):
            tmp = tempfile.mkdtemp(prefix="chaos-loop-%s-" % name)
            try:
                run = run_loop_schedule(name, tmp, quiet=as_json)
            except Exception as exc:
                run = {"schedule": name, "passed": False,
                       "error": repr(exc)}
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
            run["attempt"] = attempt
            if run.get("error") is None or attempt == 2:
                break
        runs.append(run)
    artifact = {
        "schedules": runs,
        "all_passed": all(r["passed"] for r in runs),
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    if as_json:
        print(json.dumps(artifact))
    else:
        print("chaos loop: %d schedule(s), all_passed=%s -> %s" %
              (len(runs), artifact["all_passed"], out_path))
    return 0 if artifact["all_passed"] else 1


def main(argv=None):
    ap = argparse.ArgumentParser(prog="run_chaos", description=__doc__)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--pod", action="store_true")
    ap.add_argument("--serving", action="store_true")
    ap.add_argument("--fleet", action="store_true")
    ap.add_argument("--train", action="store_true")
    ap.add_argument("--decode", action="store_true")
    ap.add_argument("--embedding", action="store_true")
    ap.add_argument("--loop", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.loop:
        out = args.out if args.out is not None \
            else os.path.join(REPO, "CHAOS_LOOP.json")
        sys.path.insert(0, REPO)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return run_loop(as_json=args.as_json, out_path=out)
    if args.embedding:
        out = args.out if args.out is not None \
            else os.path.join(REPO, "CHAOS_EMBED.json")
        sys.path.insert(0, REPO)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return run_embedding(as_json=args.as_json, out_path=out)
    if args.decode:
        out = args.out if args.out is not None \
            else os.path.join(REPO, "CHAOS_DECODE.json")
        sys.path.insert(0, REPO)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return run_decode(as_json=args.as_json, out_path=out)
    if args.fleet:
        out = args.out if args.out is not None \
            else os.path.join(REPO, "CHAOS_FLEET.json")
        sys.path.insert(0, REPO)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return run_fleet(as_json=args.as_json, out_path=out)
    if args.train:
        out = args.out if args.out is not None \
            else os.path.join(REPO, "CHAOS_TRAIN.json")
        sys.path.insert(0, REPO)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return run_train(as_json=args.as_json, out_path=out)
    if args.serving:
        out = args.out if args.out is not None \
            else os.path.join(REPO, "CHAOS_SERVING.json")
        sys.path.insert(0, REPO)
        return run_serving(as_json=args.as_json, out_path=out)
    if args.pod:
        out = args.out if args.out is not None \
            else os.path.join(REPO, "CHAOS_POD.json")
        return run_pod(as_json=args.as_json, out_path=out)
    if args.out is None:
        args.out = os.path.join(REPO, "CHAOS_REPORT.json")
    tests = QUICK_TESTS if args.quick else FULL_TESTS

    runs = [run_schedule(name, spec, tests, quiet=args.as_json)
            for name, spec in SCHEDULES.items()]
    artifact = {
        "quick": args.quick,
        "tests": tests,
        "schedules": runs,
        "total_faults": sum(r["faults"] for r in runs),
        "total_retries": sum(r["retries"] for r in runs),
        "all_passed": all(r["rc"] == 0 for r in runs),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
    if args.as_json:
        slim = dict(artifact)
        for r in slim["schedules"]:
            r.pop("tail", None)
        print(json.dumps(slim))
    else:
        print("chaos: %d schedule(s), %d faults fired, %d retries, "
              "all_passed=%s -> %s" %
              (len(runs), artifact["total_faults"],
               artifact["total_retries"], artifact["all_passed"], args.out))
    return 0 if artifact["all_passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
