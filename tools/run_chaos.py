#!/usr/bin/env python
"""Chaos runner: the tier-1 dist + serving tests under canned fault
schedules, with a JSON artifact of what was injected and what survived.

Each schedule sets ``MXNET_FAULTS`` (a seeded, deterministic fault spec —
see resilience/faults.py) and ``MXNET_FAULTS_LOG`` for the pytest process
AND every worker subprocess it spawns, runs the selected tests, then
aggregates the fault log: faults fired by site/kind, retries, reconnects,
and the final pass/fail counts.  The tests are the SAME tests that gate
normal PRs — the chaos claim is exactly "the functional contract holds
while the transport is being actively sabotaged".

Pod mode (``--pod``) runs the ELASTIC schedules instead: a root
parameter server (the pod coordinator) plus three real worker processes
mid-`Module.fit` under the supervisor, sabotaged per rank — heartbeat
drops that must NOT trip false host loss, one host SIGKILLed mid-fit
(survivors must detect it, shrink, and resume from the checkpoint), and
one hung collective (the watchdog must convert the stall into a
`CollectiveTimeoutError` and the whole pod must recover).  The artifact
(``CHAOS_POD.json``) embeds every surviving worker's
`JobSupervisor.stats()` dict — heartbeats, watchdog timeouts, hosts
lost, and the PR 5 kvstore retry/breaker counters.

Usage: python tools/run_chaos.py [--quick] [--pod] [--json] [--out PATH]
    --quick   bounded test selection (the run_tpu_parity.py stage)
    --pod     run the elastic pod schedules (writes CHAOS_POD.json)
    --json    print only the JSON artifact on stdout
    --out     also write the artifact to PATH (default CHAOS_REPORT.json,
              or CHAOS_POD.json with --pod)

Exit status: 0 when every schedule's tests passed.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import shutil
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# seeded schedules: same seed -> same per-process fault sequence, so a
# red chaos run reproduces locally with the spec string alone
SCHEDULES = {
    "flaky-connect": "seed=11;transport.connect:refuse(n=2)",
    "dropped-pushes": "seed=12;transport.send:drop(p=0.3,cmd=push,n=4)",
    "slow-peers": ("seed=13;server.dispatch:slow(ms=30,p=0.05);"
                   "serving.execute:slow(ms=10,p=0.2)"),
}

QUICK_TESTS = [
    "tests/test_dist.py::test_dist_sync_multiprocess[2-0]",
    "tests/test_dist.py::test_dist_sync_sharded_servers",
    "tests/test_serving.py::test_concurrent_clients_correct_and_ordered",
    "tests/test_serving.py::test_unload_drains_without_dropping",
]

FULL_TESTS = QUICK_TESTS + [
    "tests/test_dist.py::test_dist_sync_multiprocess[4-0]",
    "tests/test_dist.py::test_dist_sync_three_servers_uneven_ranges",
    "tests/test_dist.py::test_dist_compression_packs_the_wire",
    "tests/test_serving.py::test_drain_on_shutdown_completes_in_flight",
    "tests/test_serving.py::test_backpressure_bounded_queue",
]


def _counts(output):
    counts = {"passed": 0, "failed": 0, "errors": 0}
    for key, word in (("passed", "passed"), ("failed", "failed"),
                      ("errors", "errors?")):
        m = re.search(r"(\d+) %s\b" % word, output)
        if m:
            counts[key] = int(m.group(1))
    return counts


def _read_fault_log(path):
    """Aggregate one schedule's MXNET_FAULTS_LOG (all processes append)."""
    agg = {"faults": 0, "by_site_kind": {}, "retries": 0, "reconnects": 0}
    try:
        with open(path) as f:
            for line in f:
                try:
                    event = json.loads(line)
                except ValueError:
                    continue
                kind = event.get("event")
                if kind == "fault":
                    agg["faults"] += 1
                    key = "%s:%s" % (event.get("site"), event.get("kind"))
                    agg["by_site_kind"][key] = \
                        agg["by_site_kind"].get(key, 0) + 1
                elif kind == "retry":
                    agg["retries"] += 1
                elif kind == "reconnect":
                    agg["reconnects"] += 1
    except OSError:
        pass
    return agg


def run_schedule(name, spec, tests, quiet=False):
    log_fd, log_path = tempfile.mkstemp(prefix="chaos-%s-" % name,
                                        suffix=".jsonl")
    os.close(log_fd)
    env = dict(os.environ, MXNET_FAULTS=spec, MXNET_FAULTS_LOG=log_path,
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep +
               os.environ.get("PYTHONPATH", ""))
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "pytest", "-q", "--tb=line",
             "-p", "no:cacheprovider"] + tests,
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=1200)
        rc, output = proc.returncode, proc.stdout + proc.stderr
    except subprocess.TimeoutExpired as exc:
        # a hung schedule is a RESULT (the worst one) — record it with
        # whatever the fault log captured instead of crashing the run
        rc = -1
        output = "TIMEOUT after %ds\n%s" % (exc.timeout,
                                            (exc.stdout or "")[-1200:])
    result = {
        "schedule": name,
        "spec": spec,
        "rc": rc,
        **_counts(output),
        "duration_s": round(time.time() - t0, 1),
        **_read_fault_log(log_path),
        "tail": "\n".join(output.strip().splitlines()[-6:])[-1200:],
    }
    os.unlink(log_path)
    if not quiet:
        print("chaos[%s]: rc=%d passed=%d failed=%d faults=%d retries=%d "
              "reconnects=%d (%.1fs)" %
              (name, result["rc"], result["passed"], result["failed"],
               result["faults"], result["retries"], result["reconnects"],
               result["duration_s"]), file=sys.stderr)
    return result


# -- pod schedules: elastic multi-host supervision under sabotage -------------
# three workers mid-Module.fit; faults are injected PER RANK so each
# schedule is one deterministic pod failure story
POD_SCHEDULES = {
    # lossy control network: a burst of 3 consecutive dropped heartbeats
    # per host (0.6s silence under the 1.2s deadline) must not trip
    # false host loss — and the drops must verifiably fire
    "pod-hb-drops": {"faults": {"*": "seed=21;heartbeat.send:drop(at=2-4)"},
                     "killed": None, "min_faults": 3},
    # whole-host SIGKILL mid-fit: survivors must detect the loss within
    # the heartbeat deadline, convert the stalled round into a
    # CollectiveTimeoutError, shrink to world 2, and resume
    "pod-host-crash": {"faults": {"2": "seed=22;host.step:kill(at=4)"},
                       "killed": 2},
    # hung collective on one rank: every watchdog fires (no host is
    # dead), the full pod shrinks-in-place and resumes — no indefinite
    # hang anywhere
    "pod-hung-collective": {
        "faults": {"1": "seed=23;collective.dispatch:hang(at=9)"},
        "killed": None},
}

# the worker subprocess body is tools/pod_worker.py — ONE copy shared
# with tests/test_supervisor.py so the chaos artifact and the acceptance
# test exercise the identical protocol
POD_WORKER_PATH = os.path.join(REPO, "tools", "pod_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_pod_schedule(name, schedule, quiet=False):
    """One pod schedule: root server (coordinator) + 3 supervised workers
    mid-fit, faults injected per rank.  Returns the result dict with
    per-worker outcomes and every survivor's JobSupervisor.stats()."""
    n_workers = 3
    log_fd, log_path = tempfile.mkstemp(prefix="chaos-%s-" % name,
                                        suffix=".jsonl")
    os.close(log_fd)
    ckpt_dir = tempfile.mkdtemp(prefix="chaos-%s-ckpt-" % name)
    port = _free_port()
    base_env = dict(
        os.environ,
        DMLC_PS_ROOT_URI="127.0.0.1", DMLC_PS_ROOT_PORT=str(port),
        DMLC_NUM_WORKER=str(n_workers), DMLC_ROLE="worker",
        MXNET_KVSTORE_COLLECTIVE="0",
        # fast pod clocks: detection in ~1s, watchdog in 3s, so a whole
        # schedule (including shrink + resume) fits a CI budget
        MXNET_SUPERVISOR_HEARTBEAT_S="0.2",
        MXNET_SUPERVISOR_DEADLINE_S="1.2",
        MXNET_SUPERVISOR_COLLECTIVE_TIMEOUT_S="3.0",
        MXNET_SUPERVISOR_SHRINK_BARRIER_S="10.0",
        MXNET_PS_RECONNECT_WAIT="1.0",
        MXNET_FAULTS_LOG=log_path,
        POD_CKPT_DIR=ckpt_dir,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    base_env.pop("MXNET_FAULTS", None)
    t0 = time.time()
    server = subprocess.Popen(
        [sys.executable, "-m", "incubator_mxnet_tpu.dist.server"],
        env=dict(base_env), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, cwd=REPO)
    procs = []
    for r in range(n_workers):
        env = dict(base_env, DMLC_RANK=str(r))
        spec = schedule["faults"].get(str(r)) or schedule["faults"].get("*")
        if spec:
            env["MXNET_FAULTS"] = spec
        procs.append(subprocess.Popen(
            [sys.executable, POD_WORKER_PATH], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, cwd=REPO))
    workers = []
    hung = False
    for r, p in enumerate(procs):
        try:
            out = p.communicate(timeout=240)[0].decode()
        except subprocess.TimeoutExpired:
            # a hung worker is the exact failure this subsystem exists to
            # prevent — record it as the worst result, don't hang the run
            hung = True
            p.kill()
            out = (p.communicate()[0] or b"").decode() + "\nHUNG (killed)"
        sup_stats = None
        sha = None
        for line in out.splitlines():
            if line.startswith("SUPSTATS "):
                try:
                    sup_stats = json.loads(line[len("SUPSTATS "):])
                except ValueError:
                    pass
            elif line.startswith("PARAMS_SHA "):
                sha = line.split()[1]
        workers.append({"rank": r, "rc": p.returncode,
                        "params_sha": sha, "supervisor": sup_stats,
                        "tail": "\n".join(out.strip().splitlines()[-5:])
                                [-800:]})
    server.kill()
    server.communicate()
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    killed = schedule["killed"]
    survivors = [w for w in workers if w["rank"] != killed]
    fault_agg = _read_fault_log(log_path)
    passed = (not hung
              and all(w["rc"] == 0 for w in survivors)
              and all(w["params_sha"] is not None for w in survivors)
              and len({w["params_sha"] for w in survivors}) == 1
              and (killed is None or workers[killed]["rc"] == 137)
              and fault_agg["faults"] >= schedule.get("min_faults", 1))
    result = {
        "schedule": name,
        "specs": schedule["faults"],
        "killed_rank": killed,
        "workers": workers,
        "duration_s": round(time.time() - t0, 1),
        **fault_agg,
        "passed": passed,
    }
    os.unlink(log_path)
    if not quiet:
        print("chaos[%s]: passed=%s rcs=%s faults=%d (%.1fs)" %
              (name, passed, [w["rc"] for w in workers],
               result["faults"], result["duration_s"]), file=sys.stderr)
    return result


def run_pod(as_json=False, out_path=None):
    runs = [run_pod_schedule(name, sched, quiet=as_json)
            for name, sched in POD_SCHEDULES.items()]
    artifact = {
        "schedules": runs,
        "all_passed": all(r["passed"] for r in runs),
        "supervisor_stats": {
            r["schedule"]: [w["supervisor"] for w in r["workers"]
                            if w["supervisor"] is not None]
            for r in runs},
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    if as_json:
        slim = {"all_passed": artifact["all_passed"],
                "schedules": [{k: v for k, v in r.items()
                               if k not in ("workers",)}
                              for r in runs],
                "supervisor_stats": artifact["supervisor_stats"]}
        print(json.dumps(slim))
    else:
        print("chaos pod: %d schedule(s), all_passed=%s -> %s" %
              (len(runs), artifact["all_passed"], out_path))
    return 0 if artifact["all_passed"] else 1


def main(argv=None):
    ap = argparse.ArgumentParser(prog="run_chaos", description=__doc__)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--pod", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.pod:
        out = args.out if args.out is not None \
            else os.path.join(REPO, "CHAOS_POD.json")
        return run_pod(as_json=args.as_json, out_path=out)
    if args.out is None:
        args.out = os.path.join(REPO, "CHAOS_REPORT.json")
    tests = QUICK_TESTS if args.quick else FULL_TESTS

    runs = [run_schedule(name, spec, tests, quiet=args.as_json)
            for name, spec in SCHEDULES.items()]
    artifact = {
        "quick": args.quick,
        "tests": tests,
        "schedules": runs,
        "total_faults": sum(r["faults"] for r in runs),
        "total_retries": sum(r["retries"] for r in runs),
        "all_passed": all(r["rc"] == 0 for r in runs),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
    if args.as_json:
        slim = dict(artifact)
        for r in slim["schedules"]:
            r.pop("tail", None)
        print(json.dumps(slim))
    else:
        print("chaos: %d schedule(s), %d faults fired, %d retries, "
              "all_passed=%s -> %s" %
              (len(runs), artifact["total_faults"],
               artifact["total_retries"], artifact["all_passed"], args.out))
    return 0 if artifact["all_passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
