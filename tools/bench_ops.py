#!/usr/bin/env python
"""Op-level regression bench battery (VERDICT #10).

The round bench (`bench.py`) times whole models — a kernel regression in
one op class hides inside a 3% end-to-end drift until it is expensive to
bisect.  This battery times a REPRESENTATIVE op set directly, one JSON
artifact per run, cheap enough (< 2 min on CPU) to run per PR:

* **sparse**    — lazy row-sparse SGD/Adam optimizer updates (the
  embedding-gradient path) over a (4096, 128) table;
* **control flow** — an RNN-style `nd.contrib.foreach` scan (one fused
  scan program, T=32) plus its symbolic bound counterpart;
* **quantization** — an int8-quantized convnet forward next to its fp32
  reference (the serving int8 ladder's kernel mix);
* **attention** — the blockwise online-softmax causal attention the
  transformer LM trains and serves with, vs the naive full-score-matrix
  reference, fp32 and bf16 (plus the registered `BlockwiseAttention`
  packed op costed through its OpDef cost_meta);
* **dense reference points** — conv + matmul + softmax, so a regression
  report can say "sparse moved, dense did not".

Methodology: warmup runs first (compile + cache), then ``--iters`` timed
runs with `jax.block_until_ready` on every output; the artifact records
mean/p50/min per op.  Compare two artifacts across commits to catch a
kernel regression before the round bench does.

Each op also records its **static mxcost estimate** (flops, bytes
moved, the predicted roofline bound and step lower bound from
`analysis/cost.py`) next to the measured time, so estimate drift is
visible in the artifact itself: when a measured time moves and the
static column does not, the kernel regressed; when both move, the
graph changed.  The quantization section builds its models through
`cost.build_bench_convnet` — the SAME graphs the mxcost budget
baseline (COST_BUDGETS.json) gates.

Usage:
    python tools/bench_ops.py [--iters 20] [--out BENCH_OPS.json] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _static_of(prog):
    """The artifact's static column from a mxcost ProgramCost."""
    if prog is None:
        return None
    d = prog.as_dict()
    return {"flops": d["flops"], "bytes_moved": d["bytes_moved"],
            "predicted_bound": d["bound"],
            "arithmetic_intensity": d["arithmetic_intensity"],
            "step_time_lb_ms": d["step_time_lb_ms"],
            "profile": d["profile"]}


def _static_symbol(sym, shapes, dtypes=None, name=None):
    from incubator_mxnet_tpu.analysis import cost
    try:
        return _static_of(cost.analyze_symbol(sym, shapes=shapes,
                                              dtypes=dtypes, target=name))
    except Exception:
        return None


def _static_callable(fn, avals, name=None):
    from incubator_mxnet_tpu.analysis import cost
    try:
        return _static_of(cost.analyze_callable(fn, avals, name=name))
    except Exception:
        return None


def _timeit(fn, iters, warmup=3):
    import jax
    for _ in range(warmup):
        jax.block_until_ready(fn())
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    return {"mean_ms": round(sum(times) / len(times), 4),
            "p50_ms": round(times[len(times) // 2], 4),
            "min_ms": round(times[0], 4),
            "iters": iters}


def _sparse_ops(mx, nd, np):
    """Lazy row-sparse optimizer updates: the embedding-table gradient
    path (touched rows only; untouched rows must stay bit-identical)."""
    from incubator_mxnet_tpu.ndarray.sparse import RowSparseNDArray
    rng = np.random.RandomState(0)
    V, D, K = 4096, 128, 64
    rows = np.sort(rng.choice(V, K, replace=False)).astype(np.int64)
    gvals = rng.randn(K, D).astype("f4")

    def bench(opt_name, opt):
        w = nd.array(rng.randn(V, D).astype("f4"))
        states = [nd.zeros((V, D)) for _ in range(
            2 if opt_name == "adam" else 1)]
        state = states if opt_name == "adam" else states[0]

        def run():
            opt.update(0, w, RowSparseNDArray(gvals, rows, (V, D)), state)
            return w._data
        return run

    # the lazy row-sparse update runs through the host-resident sparse
    # path (see ndarray/sparse.py) — no traced program to walk, but the
    # rows-touched x row-bytes model (cost.analyze_embedding) gives the
    # static column exactly: cost scales with touched rows, not table size
    from incubator_mxnet_tpu.analysis import cost as _mxcost

    def _embed_static(kind):
        try:
            return _static_of(_mxcost.analyze_embedding(
                V, D, K, kind=kind, name=f"sparse.{kind}_lazy"))
        except Exception:
            return None

    # embedding-lookup lane: the serving/fit hot path — a batched device
    # gather from a hot-row cache buffer through the unified program cache
    from incubator_mxnet_tpu.embedding import HotRowCache
    cache = HotRowCache(D, capacity=max(256, K), name="bench")
    cache.insert(rows, rng.randn(K, D).astype("f4"))
    lookup_ids = rng.choice(rows, 256, replace=True).astype(np.int64)

    def run_lookup():
        out, _h, _m = cache.lookup(lookup_ids, pull_fn=None)
        return out

    return {
        "sparse.sgd_momentum_lazy": (
            bench("sgd", mx.optimizer.SGD(learning_rate=0.1, momentum=0.9,
                                          lazy_update=True)),
            f"({V},{D}) table, {K} rows", _embed_static("sgd_momentum")),
        "sparse.adam_lazy": (
            bench("adam", mx.optimizer.Adam(learning_rate=0.001,
                                            lazy_update=True)),
            f"({V},{D}) table, {K} rows", _embed_static("adam")),
        "sparse.embedding_lookup": (
            run_lookup,
            f"({V},{D}) table, 256 hot ids",
            _static_of(_mxcost.analyze_embedding(
                V, D, 256, kind="lookup",
                name="sparse.embedding_lookup"))),
    }


def _control_flow_ops(mx, nd, np):
    """RNN-style scan through `_foreach`: ONE scan program per shape,
    imperative and symbolic-bound variants."""
    rng = np.random.RandomState(1)
    T, B, H = 32, 16, 64
    xnp = rng.rand(T, B, H).astype("f4")
    snp = rng.rand(B, H).astype("f4")
    wnp = rng.rand(H, H).astype("f4")

    wa = nd.array(wnp)
    xa, sa = nd.array(xnp), nd.array(snp)

    def cell(x, s):
        out = nd.tanh(nd.dot(x, wa) + s)
        return out, out

    def run_imperative():
        outs, states = nd.contrib.foreach(cell, xa, sa)
        return outs._data

    data = mx.sym.Variable("data")
    init = mx.sym.Variable("init")
    w = mx.sym.Variable("w")

    def body(x, s):
        out = mx.sym.Activation(
            mx.sym.broadcast_add(mx.sym.dot(x, w), s), act_type="tanh")
        return out, out

    outs, states = mx.sym.contrib.foreach(body, data, init)
    g = mx.sym.Group([outs, states])
    exe = g.simple_bind(ctx=mx.cpu(), grad_req="null",
                        data=(T, B, H), init=(B, H), w=(H, H))

    def run_symbolic():
        o = exe.forward(is_train=False, data=xa, init=sa, w=wa)
        return o[0]._data

    shape = f"T={T} batch={B} hidden={H}"
    from incubator_mxnet_tpu.analysis import cost as _mxcost
    try:
        # executor-level analysis costs the scan BODY x trip count
        # (the symbol walk cannot see through the _foreach node)
        static = _static_of(_mxcost.analyze_executor(
            exe, name="control_flow.foreach_rnn"))
    except Exception:
        static = None
    return {"control_flow.foreach_rnn_imperative": (run_imperative, shape,
                                                    static),
            "control_flow.foreach_rnn_symbolic": (run_symbolic, shape,
                                                  static)}


def _quantization_ops(mx, nd, np):
    """INT8 convnet forward vs its fp32 reference executor.  The graphs
    come from `analysis.cost.build_bench_convnet` — the SAME models the
    mxcost budget baseline gates, so the measured and static columns
    describe one program."""
    from incubator_mxnet_tpu.analysis.cost import (build_bench_convnet,
                                                   BENCH_SHAPE)
    from incubator_mxnet_tpu.contrib.quantization import quantize_model
    rng = np.random.RandomState(2)
    sym, _shapes = build_bench_convnet("float32")

    shape = BENCH_SHAPE
    arg_shapes, _, aux_shapes = sym.infer_shape(data=shape)
    args = {n: nd.array(rng.normal(0, 0.5, s).astype("f4"))
            for n, s in zip(sym.list_arguments(), arg_shapes)
            if n != "data"}
    auxs = {n: nd.zeros(s) for n, s in
            zip(sym.list_auxiliary_states(), aux_shapes)}
    x = nd.array(rng.normal(0, 1, shape).astype("f4"))

    fexe = sym.simple_bind(ctx=mx.cpu(), grad_req="null", data=shape)
    fexe.copy_params_from(args, auxs)

    qsym, qargs, qauxs = quantize_model(sym, args, auxs, calib_mode="none")
    qexe = qsym.simple_bind(ctx=mx.cpu(), grad_req="null", data=shape)
    qexe.copy_params_from(qargs, qauxs, allow_extra_params=True)

    def run_fp32():
        return fexe.forward(is_train=False, data=x)[0]._data

    def run_int8():
        return qexe.forward(is_train=False, data=x)[0]._data

    s = "x".join(str(d) for d in shape)
    qdtypes = {n: str(a.dtype) for n, a in qargs.items()}
    return {"quantization.convnet_fp32": (
                run_fp32, s,
                _static_symbol(sym, {"data": shape},
                               name="quantization.convnet_fp32")),
            "quantization.convnet_int8": (
                run_int8, s,
                _static_symbol(qsym, {"data": shape}, dtypes=qdtypes,
                               name="quantization.convnet_int8"))}


def _attention_ops(mx, nd, np):
    """Causal self-attention: the blockwise online-softmax kernel the
    transformer LM trains and serves with, next to the naive
    full-score-matrix reference, in fp32 and the bf16 serving dtype.
    The two compute identical math (tests/test_ring_attention.py), so
    the measured gap is pure kernel shape — and the static column is
    the SAME flops either way, which is the point: mxcost estimates
    the op, not the tiling."""
    import functools
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops.attention import naive_attention
    from incubator_mxnet_tpu.parallel.ring_attention import \
        blockwise_attention
    rng = np.random.RandomState(4)
    B, T, H, D = 2, 128, 4, 32
    C = H * D

    def lanes(dtype, tag):
        q4, k4, v4 = (jnp.asarray(rng.randn(B, T, H, D), dtype=dtype)
                      for _ in range(3))
        pack = lambda a: a.reshape(B, T, C)  # noqa: E731
        blockwise = jax.jit(functools.partial(
            blockwise_attention, block_size=64, causal=True))
        naive = jax.jit(functools.partial(
            naive_attention, num_heads=H, causal=True))
        shape = f"{B}x{T}x{H}x{D} {tag}"
        aval4 = [jax.ShapeDtypeStruct((B, T, H, D), dtype)] * 3
        aval3 = [jax.ShapeDtypeStruct((B, T, C), dtype)] * 3
        return {
            f"attention.blockwise_{tag}": (
                lambda: blockwise(q4, k4, v4), shape,
                _static_callable(blockwise, aval4,
                                 name=f"attention.blockwise_{tag}")),
            f"attention.naive_{tag}": (
                lambda: naive(pack(q4), pack(k4), pack(v4)), shape,
                _static_callable(naive, aval3,
                                 name=f"attention.naive_{tag}")),
        }

    ops = {}
    ops.update(lanes(jnp.float32, "fp32"))
    ops.update(lanes(jnp.bfloat16, "bf16"))
    # the registered packed-face op, costed through its OpDef cost_meta
    # (the estimate the scheduler sees) rather than a traced callable
    qp = nd.array(rng.randn(B, T, C).astype("f4"))
    data = mx.sym.Variable("data")
    asym = mx.sym.BlockwiseAttention(data, data, data, num_heads=H,
                                     causal=True)
    ops["attention.op_blockwise_fp32"] = (
        lambda: nd.BlockwiseAttention(qp, qp, qp, num_heads=H,
                                      causal=True)._data,
        f"{B}x{T}x{C} packed",
        _static_symbol(asym, {"data": (B, T, C)},
                       name="attention.op_blockwise_fp32"))
    return ops


def _dense_ops(mx, nd, np):
    """Dense reference points: a regression report should be able to say
    'sparse moved, dense did not'."""
    rng = np.random.RandomState(3)
    a = nd.array(rng.randn(256, 256).astype("f4"))
    b = nd.array(rng.randn(256, 256).astype("f4"))
    x = nd.array(rng.randn(8, 16, 32, 32).astype("f4"))
    wconv = nd.array(rng.randn(16, 16, 3, 3).astype("f4"))
    logits = nd.array(rng.randn(64, 1000).astype("f4"))

    import jax
    import jax.numpy as jnp

    def _conv_ref(xv, wv):
        return jax.lax.conv_general_dilated(
            xv, wv, window_strides=(1, 1), padding=[(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    f4 = np.float32
    return {
        "dense.matmul_256": (
            lambda: nd.dot(a, b)._data, "256x256",
            _static_callable(jnp.dot,
                             [jax.ShapeDtypeStruct((256, 256), f4)] * 2,
                             name="dense.matmul_256")),
        "dense.conv3x3": (
            lambda: nd.Convolution(x, wconv, no_bias=True, kernel=(3, 3),
                                   num_filter=16, pad=(1, 1))._data,
            "8x16x32x32",
            _static_callable(
                _conv_ref,
                [jax.ShapeDtypeStruct((8, 16, 32, 32), f4),
                 jax.ShapeDtypeStruct((16, 16, 3, 3), f4)],
                name="dense.conv3x3")),
        "dense.softmax": (
            lambda: nd.softmax(logits)._data, "64x1000",
            _static_callable(jax.nn.softmax,
                             [jax.ShapeDtypeStruct((64, 1000), f4)],
                             name="dense.softmax")),
    }


def run_battery(iters=20):
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd

    ops = {}
    for builder in (_sparse_ops, _control_flow_ops, _quantization_ops,
                    _attention_ops, _dense_ops):
        ops.update(builder(mx, nd, np))

    results = {}
    for name in sorted(ops):
        fn, shape, static = ops[name]
        results[name] = dict(_timeit(fn, iters), shape=shape,
                             static=static)
    return results


def main(argv=None):
    ap = argparse.ArgumentParser(prog="bench_ops", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--out", default=os.path.join(REPO, "BENCH_OPS.json"),
                    help="artifact path ('' skips writing)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    t0 = time.time()
    results = run_battery(iters=args.iters)

    import subprocess
    try:
        rev = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             cwd=REPO, capture_output=True, text=True,
                             timeout=10).stdout.strip() or None
    except Exception:
        rev = None
    import jax
    artifact = {
        "ops": results,
        "iters": args.iters,
        "duration_s": round(time.time() - t0, 1),
        "git_rev": rev,
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
    if args.as_json:
        print(json.dumps(artifact, indent=1))
    else:
        width = max(len(n) for n in results)
        for name in sorted(results):
            r = results[name]
            st = r.get("static")
            tail = "" if not st else \
                "   static %.1f MFLOP %s-bound" % (
                    st["flops"] / 1e6, st["predicted_bound"])
            print(f"{name:<{width}}  mean {r['mean_ms']:8.3f} ms   "
                  f"p50 {r['p50_ms']:8.3f} ms   ({r['shape']}){tail}")
        print(f"bench_ops: {len(results)} op(s) in "
              f"{artifact['duration_s']:g}s"
              + (f" -> {args.out}" if args.out else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
