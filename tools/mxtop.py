#!/usr/bin/env python
"""mxtop — live terminal status over the fleet's scrape plane.

Points at any set of transport endpoints that answer the ``metrics``
frame — replica workers, host daemons (``serving.hostd``), parameter
servers, or a standalone `obs.scrape.MetricsEndpoint` — and renders
one fleet-wide status view: per-replica QPS / p99 / queue depth /
shed, per-host liveness and worker counts, kvstore bytes/step and
bucket economy, guardian skip/rollback/quarantine counts, program
cache traffic.

Usage:
    python tools/mxtop.py ENDPOINT [ENDPOINT ...] [options]
        ENDPOINT: host:port / :port / port (transport spellings)
    --json           one snapshot as JSON ({"endpoints", "fleet"}) and
                     exit — the scriptable face (the obs CI stage and
                     dashboards consume this)
    --interval S     live refresh period (default 2.0)
    --once           render one text frame and exit (no ANSI loop)
    --timeout S      per-endpoint scrape timeout (default 5.0)

Aggregation: the ``fleet`` block sums numeric values that share a
dotted name across endpoints (counters add; point-in-time gauges add
too — a fleet-wide queue depth IS the sum of per-replica depths) and
keeps per-endpoint blocks verbatim for anything that must not be
summed.  Unreachable endpoints are listed, never fatal — a half-dead
fleet is exactly when you need the numbers.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def snapshot(endpoints, timeout=5.0):
    """Scrape every endpoint once -> {"endpoints", "fleet", "unreachable"}."""
    from incubator_mxnet_tpu.obs.scrape import scrape
    per, unreachable = {}, []
    for ep in endpoints:
        try:
            per[str(ep)] = scrape(ep, timeout=timeout)["values"]
        except Exception as exc:
            unreachable.append({"endpoint": str(ep),
                                "error": f"{type(exc).__name__}: {exc}"})
    fleet = {}
    for values in per.values():
        for name, v in values.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                fleet[name] = fleet.get(name, 0) + v
    return {"endpoints": per, "fleet": fleet, "unreachable": unreachable,
            "time": round(time.time(), 3)}


def _namespace(values, prefix):
    pfx = prefix + "."
    return {k[len(pfx):]: v for k, v in values.items()
            if k.startswith(pfx)}


def _fmt(v, nd=1):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render(snap):
    """One text frame over a snapshot (shared by --once and the loop)."""
    lines = []
    fleet = snap["fleet"]
    lines.append("mxtop — %d endpoint(s), %d unreachable    %s"
                 % (len(snap["endpoints"]), len(snap["unreachable"]),
                    time.strftime("%H:%M:%S")))
    for u in snap["unreachable"]:
        lines.append("  DOWN %-22s %s" % (u["endpoint"], u["error"][:60]))
    # -- serving: per-replica/model QPS, p99, queue depth --------------------
    serving = {}
    for ep, values in snap["endpoints"].items():
        for name, v in values.items():
            if not name.startswith("serving."):
                continue
            rest = name.split(".", 1)[1]
            model, _, field = rest.partition(".")
            serving.setdefault((ep, model), {})[field] = v
    if serving:
        lines.append("")
        lines.append("  %-18s %-14s %8s %9s %7s %7s %7s"
                     % ("SERVING", "endpoint", "qps", "p99_ms",
                        "queue", "shed", "resp"))
        for (ep, model), f in sorted(serving.items()):
            lines.append("  %-18s %-14s %8s %9s %7s %7s %7s"
                         % (model[:18], ep[-14:], _fmt(f.get("qps")),
                            _fmt(f.get("p99_ms")),
                            _fmt(f.get("queue_depth"), 0),
                            _fmt(f.get("shed"), 0),
                            _fmt(f.get("responses"), 0)))
    # -- router / fleet ------------------------------------------------------
    router = _namespace(fleet, "router")
    if router:
        lines.append("")
        lines.append("  ROUTER  inflight=%s failovers=%s lost=%s "
                     "dup_suppressed=%s swaps=%s"
                     % (_fmt(router.get("inflight"), 0),
                        _fmt(router.get("failovers"), 0),
                        _fmt(router.get("replicas_lost"), 0),
                        _fmt(router.get("duplicates_suppressed"), 0),
                        _fmt(router.get("swaps_committed"), 0)))
    fl = _namespace(fleet, "fleet")
    if fl:
        hosts_alive = sum(v for k, v in fl.items()
                          if k.startswith("hosts.") and k.endswith(".alive"))
        lines.append("  FLEET   live=%s target=%s ups=%s downs=%s "
                     "hosts_lost=%s hosts_alive=%s backfill_s=%s"
                     % (_fmt(fl.get("live_replicas"), 0),
                        _fmt(fl.get("target"), 0),
                        _fmt(fl.get("scale_ups"), 0),
                        _fmt(fl.get("scale_downs"), 0),
                        _fmt(fl.get("hosts_lost"), 0),
                        _fmt(hosts_alive, 0),
                        _fmt(fl.get("backfill_latency_s"))))
    hostd = _namespace(fleet, "hostd")
    if hostd:
        lines.append("  HOSTS   workers=%s spawns=%s"
                     % (_fmt(hostd.get("workers"), 0),
                        _fmt(hostd.get("spawns"), 0)))
    # -- kvstore -------------------------------------------------------------
    kv = _namespace(fleet, "kvstore")
    if kv:
        lines.append("")
        lines.append("  KVSTORE pushes=%s dispatches=%s buckets=%s "
                     "MB_reduced=%s fill=%s overlap=%s"
                     % (_fmt(kv.get("batched_pushes"), 0),
                        _fmt(kv.get("allreduce_dispatches"), 0),
                        _fmt(kv.get("buckets"), 0),
                        _fmt((kv.get("bytes_reduced") or 0) / (1 << 20)),
                        _fmt(kv.get("avg_bucket_fill"), 2),
                        _fmt(kv.get("overlap_ratio"), 2)))
    # -- guardian / supervisor ----------------------------------------------
    gd = _namespace(fleet, "guardian")
    if gd:
        lines.append("  GUARD   steps=%s skips=%s spikes=%s rollbacks=%s "
                     "quarantined=%s"
                     % (_fmt(gd.get("steps_observed"), 0),
                        _fmt(gd.get("skips"), 0),
                        _fmt(gd.get("spikes"), 0),
                        _fmt(gd.get("rollbacks"), 0),
                        _fmt(gd.get("quarantined"), 0)))
    sup = _namespace(fleet, "supervisor")
    if sup:
        lines.append("  SUPERV  step=%s heartbeats=%s hosts_lost=%s "
                     "watchdog_timeouts=%s stragglers=%s"
                     % (_fmt(sup.get("step"), 0),
                        _fmt(sup.get("heartbeats"), 0),
                        _fmt(sup.get("hosts_lost"), 0),
                        _fmt(sup.get("collective_timeouts"), 0),
                        _fmt(sup.get("stragglers_flagged"), 0)))
    cache = _namespace(fleet, "cache.counters")
    if cache:
        lines.append("  CACHE   compiles=%s disk_hits=%s disk_misses=%s "
                     "mem_hits=%s stores=%s lower_s=%s compile_s=%s"
                     % (_fmt(cache.get("compiles"), 0),
                        _fmt(cache.get("disk_hits"), 0),
                        _fmt(cache.get("disk_misses"), 0),
                        _fmt(cache.get("mem_hits"), 0),
                        _fmt(cache.get("stores"), 0),
                        _fmt(cache.get("lower_s_total"), 2),
                        _fmt(cache.get("compile_s_total"), 2)))
    worker = _namespace(fleet, "worker")
    if worker:
        lines.append("  WORKER  executed=%s dedup_hits=%s outstanding=%s"
                     % (_fmt(worker.get("executed"), 0),
                        _fmt(worker.get("dedup_hits"), 0),
                        _fmt(worker.get("outstanding"), 0)))
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxtop", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("endpoints", nargs="+",
                    help="transport endpoints answering 'metrics' frames")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print ONE snapshot as JSON and exit")
    ap.add_argument("--once", action="store_true",
                    help="render one text frame and exit")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)

    if args.as_json:
        print(json.dumps(snapshot(args.endpoints, timeout=args.timeout),
                         indent=1))
        return 0
    if args.once:
        print(render(snapshot(args.endpoints, timeout=args.timeout)))
        return 0
    try:
        while True:
            frame = render(snapshot(args.endpoints, timeout=args.timeout))
            # clear + home, then the frame (plain ANSI; no curses dep)
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
