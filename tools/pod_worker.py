#!/usr/bin/env python
"""One elastic pod worker: the subprocess body both `tools/run_chaos.py
--pod` and `tests/test_supervisor.py` launch (one copy — the chaos
artifact and the acceptance test must not drift apart).

Runs a small supervised `Module.fit(kvstore='dist_sync')` against the
coordinator named by the DMLC env, with elastic checkpointing, then
prints the machine-readable protocol the launchers parse:

    SUPSTATS {json}      JobSupervisor.stats() of the final attempt
    COMPILES N           unified-program-cache compiles this process
    PARAMS_SHA hex       sha256 over the sorted final params
    worker OK rank=R

Env: ``POD_CKPT_DIR`` (shared checkpoint directory, required),
``POD_RESUME=1`` (resume the directory's run — the control lane of the
bit-identical gate), ``POD_SCALING=1`` (record a per-world-size
throughput curve across shrinks and print it as ``SCALING {json}`` —
the chaos ``pod-scaling`` schedule's artifact), and the usual
DMLC_*/MXNET_* knobs (fault schedules ride ``MXNET_FAULTS``).
"""
import hashlib
import json
import logging
import os
import time

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")
logging.basicConfig(level=logging.INFO)

import incubator_mxnet_tpu as mx                      # noqa: E402
from incubator_mxnet_tpu import sym                   # noqa: E402
from incubator_mxnet_tpu.io import NDArrayIter        # noqa: E402


def main():
    d = sym.Variable("data")
    f1 = sym.FullyConnected(d, num_hidden=8, name="fc1")
    a1 = sym.Activation(f1, act_type="relu")
    f2 = sym.FullyConnected(a1, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(f2, name="softmax")
    mx.random.seed(11)
    np.random.seed(11)
    X = np.random.RandomState(2).randn(48, 10).astype("f4")
    y = (np.arange(48) % 4).astype("f4")
    it = NDArrayIter(X, y, batch_size=8, shuffle=False)
    mod = mx.mod.Module(net, context=mx.cpu())
    marks = []          # (world_size, perf_counter) per completed batch

    def scaling_cb(param):
        sup = mod._supervisor
        world = sup.stats()["world_size"] if sup is not None else \
            int(os.environ.get("DMLC_NUM_WORKER", 1))
        marks.append((world, time.perf_counter()))

    scaling = os.environ.get("POD_SCALING") == "1"
    mod.fit(it, kvstore="dist_sync", optimizer="sgd",
            optimizer_params={"learning_rate": 0.1}, num_epoch=2,
            checkpoint_dir=os.environ["POD_CKPT_DIR"],
            checkpoint_period=1, checkpoint_keep_last=100,
            resume=os.environ.get("POD_RESUME") == "1",
            batch_end_callback=scaling_cb if scaling else None)
    if scaling:
        # the scaling curve across shrinks: this worker's steps and
        # steps/s per world size it trained at (world 3 pre-kill,
        # world 2 post-shrink in the sabotaged lane)
        curve = {}
        for (world, t) in marks:
            pt = curve.setdefault(world, {"steps": 0, "t0": t, "t1": t})
            pt["steps"] += 1
            pt["t1"] = t
        print("SCALING " + json.dumps({
            str(w): {"steps": pt["steps"],
                     "steps_per_s": round(
                         (pt["steps"] - 1) / max(pt["t1"] - pt["t0"],
                                                 1e-9), 2)}
            for w, pt in sorted(curve.items())}))
    sup = mod._supervisor
    if sup is not None:
        print("SUPSTATS " + json.dumps(sup.stats()))
    from incubator_mxnet_tpu import compile as _compile
    print("COMPILES %d" % _compile.stats()["counters"]["compiles"])
    args, _ = mod.get_params()
    blob = b"".join(args[k].asnumpy().tobytes() for k in sorted(args))
    print("PARAMS_SHA " + hashlib.sha256(blob).hexdigest())
    kv = getattr(mod, "_kvstore", None)
    if kv is not None:
        # the protocol 'stop' lets a serve_forever coordinator reach its
        # shutdown quorum once every (post-shrink) worker finished
        kv.close()
    print("worker OK rank=%s" % os.environ.get("DMLC_RANK"))


if __name__ == "__main__":
    main()
