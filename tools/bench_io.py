#!/usr/bin/env python
"""Input-pipeline throughput bench (reference counterpart:
`src/io/iter_image_recordio_2.cc` threaded decode, measured by
`tests/python/train` pipelines).

Builds a synthetic JPEG corpus packed into a .rec file, then measures
ImageRecordIter img/s across thread counts.  Prints one JSON line.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def h2d_probe(batch, image, n_bufs=12):
    """THE h2d three-way probe, shared by bench.py's io lane and
    tools/run_io_bench.py's CI gate (one implementation so the BENCH
    artifact and the gate always measure the same thing): host memcpy
    bandwidth (the physical ceiling a staged transfer can approach),
    the BLOCKING `device_put` baseline (what the pre-ring training loop
    paid per batch — the 13.8 MB/s BENCH_r05 number on the dev
    tunnel), and the PIPELINED staging-ring rate (transfers on the
    mx-io-h2d thread, the consumer pops device-resident batches).
    Returns MB/s numbers plus the ring's own stats."""
    import threading

    import jax
    from incubator_mxnet_tpu.io_plane import H2DRing, RingPlacement

    buf = np.random.rand(batch, 3, image, image).astype("f4")
    nbytes = buf.nbytes
    # memcpy reference: one host copy of the same bytes
    dst = np.empty_like(buf)
    t0 = time.perf_counter()
    reps = 0
    while time.perf_counter() - t0 < 0.2 or reps < 3:
        np.copyto(dst, buf)
        reps += 1
    memcpy = nbytes * reps / (time.perf_counter() - t0) / 1e6
    # blocking baseline: the transfer serializes with the caller
    t0 = time.perf_counter()
    for _ in range(3):
        jax.block_until_ready(jax.device_put(buf))
    blocking = 3 * nbytes / (time.perf_counter() - t0) / 1e6
    # pipelined ring: a feeder stages+transfers while the consumer pops
    ring = H2DRing(RingPlacement(), name="bench")

    def _feed():
        for _ in range(n_bufs):
            if not ring.put([buf]):
                return
        ring.put_end()

    th = threading.Thread(target=_feed, daemon=True, name="mx-io-h2d")
    t0 = time.perf_counter()
    th.start()
    got = 0
    while True:
        try:
            ring.get()
        except StopIteration:
            break
        got += 1
    dt = time.perf_counter() - t0
    th.join(timeout=10)
    st = ring.ring_stats()
    ring.close()
    pipelined = got * nbytes / dt / 1e6
    return {
        "bytes_per_batch": int(nbytes),
        "memcpy_MBps": round(memcpy, 1),
        "blocking_MBps": round(blocking, 1),
        "pipelined_MBps": round(pipelined, 1),
        "pipelined_vs_blocking": round(pipelined / max(blocking, 1e-9), 2),
        "ring": {k: round(v, 4) if isinstance(v, float) else v
                 for k, v in st.items()},
    }


def build_corpus(path, n=1024, size=256, quality=90):
    import cv2
    from incubator_mxnet_tpu import recordio
    rng = np.random.RandomState(0)
    rec = recordio.MXRecordIO(path, "w")
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
        # random noise compresses badly; blur for realistic jpeg sizes
        img = cv2.GaussianBlur(img, (9, 9), 4)
        ok, enc = cv2.imencode(".jpg", img,
                               [cv2.IMWRITE_JPEG_QUALITY, quality])
        assert ok
        rec.write(recordio.pack(
            recordio.IRHeader(0, float(i % 10), i, 0), enc.tobytes()))
    rec.close()


def measure(path, batch_size, shape, threads, epochs=1,
            device_augment=False):
    from incubator_mxnet_tpu import io as mxio
    it = mxio.ImageRecordIter(
        path_imgrec=path, data_shape=shape, batch_size=batch_size,
        rand_crop=True, rand_mirror=True,
        mean_r=123.68, mean_g=116.78, mean_b=103.94,
        std_r=58.4, std_g=57.1, std_b=57.4,
        preprocess_threads=threads, prefetch_buffer=8,
        device_augment=device_augment)
    for i, batch in enumerate(it):      # warmup: jax init + jit caches
        if i >= 2:
            break
    n_img = 0
    t0 = time.perf_counter()
    for _ in range(epochs):
        it.reset()
        for batch in it:
            n_img += batch.data[0].shape[0]
    dt = time.perf_counter() - t0
    return n_img / dt


def main():
    ap = argparse.ArgumentParser()
    # corpus >= ~24 batches at the default batch size: a smaller corpus
    # makes the measured window warmup/edge-dominated (epoch boundaries,
    # pool refill) and under-reports steady-state throughput
    ap.add_argument("--n", type=int, default=3072)
    ap.add_argument("--size", type=int, default=256)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--crop", type=int, default=224)
    ap.add_argument("--threads", type=int, nargs="+", default=[1, 4, 8, 16])
    args = ap.parse_args()

    with tempfile.TemporaryDirectory() as td:
        rec = os.path.join(td, "corpus.rec")
        build_corpus(rec, n=args.n, size=args.size)
        from incubator_mxnet_tpu import native
        results = {}
        for t in args.threads:
            results[f"threads_{t}"] = round(
                measure(rec, args.batch, (3, args.crop, args.crop), t), 1)
        # device-augment lane: host stops at decode + uint8 crop (the
        # fp32 normalize/transpose finish moves into the training
        # program) — the training-relevant host rate on TPU
        for t in args.threads:
            results[f"device_augment_threads_{t}"] = round(
                measure(rec, args.batch, (3, args.crop, args.crop), t,
                        device_augment=True), 1)
        best = max(results.values())
        # the per-core ceiling: raw JPEG decode alone (no unpack/augment/
        # batch/queue).  pipeline/ceiling says how much headroom the
        # surrounding machinery leaves; threads are clamped to cores, so
        # on an N-core host the pipeline scales to ~N x this per-core rate
        import cv2
        import numpy as np
        rng = np.random.RandomState(0)
        enc = []
        for i in range(64):
            img = cv2.GaussianBlur(rng.randint(
                0, 255, (args.size, args.size, 3), dtype=np.uint8), (9, 9), 4)
            enc.append(cv2.imencode(".jpg", img)[1])
        t0 = time.perf_counter()
        for _ in range(4):
            for e in enc:
                cv2.imdecode(e, cv2.IMREAD_COLOR)
        ceiling = 256 / (time.perf_counter() - t0)
        print(json.dumps({
            "metric": "image_record_iter_img_per_sec",
            "value": best, "unit": "img/sec",
            "native": native.lib() is not None,
            "decode_ceiling_1core": round(ceiling, 1),
            "pipeline_efficiency": round(best / ceiling, 3),
            "cores": os.cpu_count(),
            **results}))


if __name__ == "__main__":
    main()
