#!/usr/bin/env python
"""Input-pipeline throughput bench (reference counterpart:
`src/io/iter_image_recordio_2.cc` threaded decode, measured by
`tests/python/train` pipelines).

Builds a synthetic JPEG corpus packed into a .rec file, then measures
ImageRecordIter img/s across thread counts.  Prints one JSON line.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_corpus(path, n=1024, size=256, quality=90):
    import cv2
    from incubator_mxnet_tpu import recordio
    rng = np.random.RandomState(0)
    rec = recordio.MXRecordIO(path, "w")
    for i in range(n):
        img = rng.randint(0, 255, (size, size, 3), dtype=np.uint8)
        # random noise compresses badly; blur for realistic jpeg sizes
        img = cv2.GaussianBlur(img, (9, 9), 4)
        ok, enc = cv2.imencode(".jpg", img,
                               [cv2.IMWRITE_JPEG_QUALITY, quality])
        assert ok
        rec.write(recordio.pack(
            recordio.IRHeader(0, float(i % 10), i, 0), enc.tobytes()))
    rec.close()


def measure(path, batch_size, shape, threads, epochs=1,
            device_augment=False):
    from incubator_mxnet_tpu import io as mxio
    it = mxio.ImageRecordIter(
        path_imgrec=path, data_shape=shape, batch_size=batch_size,
        rand_crop=True, rand_mirror=True,
        mean_r=123.68, mean_g=116.78, mean_b=103.94,
        std_r=58.4, std_g=57.1, std_b=57.4,
        preprocess_threads=threads, prefetch_buffer=8,
        device_augment=device_augment)
    for i, batch in enumerate(it):      # warmup: jax init + jit caches
        if i >= 2:
            break
    n_img = 0
    t0 = time.perf_counter()
    for _ in range(epochs):
        it.reset()
        for batch in it:
            n_img += batch.data[0].shape[0]
    dt = time.perf_counter() - t0
    return n_img / dt


def main():
    ap = argparse.ArgumentParser()
    # corpus >= ~24 batches at the default batch size: a smaller corpus
    # makes the measured window warmup/edge-dominated (epoch boundaries,
    # pool refill) and under-reports steady-state throughput
    ap.add_argument("--n", type=int, default=3072)
    ap.add_argument("--size", type=int, default=256)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--crop", type=int, default=224)
    ap.add_argument("--threads", type=int, nargs="+", default=[1, 4, 8, 16])
    args = ap.parse_args()

    with tempfile.TemporaryDirectory() as td:
        rec = os.path.join(td, "corpus.rec")
        build_corpus(rec, n=args.n, size=args.size)
        from incubator_mxnet_tpu import native
        results = {}
        for t in args.threads:
            results[f"threads_{t}"] = round(
                measure(rec, args.batch, (3, args.crop, args.crop), t), 1)
        # device-augment lane: host stops at decode + uint8 crop (the
        # fp32 normalize/transpose finish moves into the training
        # program) — the training-relevant host rate on TPU
        for t in args.threads:
            results[f"device_augment_threads_{t}"] = round(
                measure(rec, args.batch, (3, args.crop, args.crop), t,
                        device_augment=True), 1)
        best = max(results.values())
        # the per-core ceiling: raw JPEG decode alone (no unpack/augment/
        # batch/queue).  pipeline/ceiling says how much headroom the
        # surrounding machinery leaves; threads are clamped to cores, so
        # on an N-core host the pipeline scales to ~N x this per-core rate
        import cv2
        import numpy as np
        rng = np.random.RandomState(0)
        enc = []
        for i in range(64):
            img = cv2.GaussianBlur(rng.randint(
                0, 255, (args.size, args.size, 3), dtype=np.uint8), (9, 9), 4)
            enc.append(cv2.imencode(".jpg", img)[1])
        t0 = time.perf_counter()
        for _ in range(4):
            for e in enc:
                cv2.imdecode(e, cv2.IMREAD_COLOR)
        ceiling = 256 / (time.perf_counter() - t0)
        print(json.dumps({
            "metric": "image_record_iter_img_per_sec",
            "value": best, "unit": "img/sec",
            "native": native.lib() is not None,
            "decode_ceiling_1core": round(ceiling, 1),
            "pipeline_efficiency": round(best / ceiling, 3),
            "cores": os.cpu_count(),
            **results}))


if __name__ == "__main__":
    main()
