#!/usr/bin/env python
"""Sharded sparse-embedding bench: the mxembed economics as one JSON
artifact (``BENCH_EMBED.json``).

The tier exists for ONE workload shape: an embedding table too big for
a single device's HBM, hit by power-law id traffic.  Rows live sharded
across parameter-server processes; the worker keeps only a bounded
device-resident hot-row cache.  This bench certifies the three claims
that make that design worth its complexity:

* **over-HBM certification** — the benched table is >= 4x the modeled
  single-device HBM budget (``MXNET_EMBED_HBM_BUDGET_MB``), yet it
  trains through ``Module.fit`` (row-sparse pushes, shard-side lazy
  updates) and serves through a `ReplicaRouter` tower fleet with
  results matching a direct forward pass;
* **hot-cache economics** — steady-state lookups of a hot working set
  (device-cache gathers) sustain >= 2x the cold-pull throughput
  (every row over the wire), with ZERO recompiles inside the timed
  hot region (the padded gather/scatter ladder is warm: one
  executable replays);
* **lookup latency under load** — p50/p99 of per-lookup latency while
  4 threads hammer the table concurrently (reported; absolute numbers
  vary across CI machines, so the gate is completion + finiteness).

Usage: python tools/run_embed_bench.py [--quick] [--json] [--out PATH]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the bench models a 1 MB device budget so a megabyte-scale table IS
# the "millions of users" shape without minutes of row-init time
os.environ["MXNET_EMBED_HBM_BUDGET_MB"] = "1"


def _spawn(n):
    from incubator_mxnet_tpu.dist.server import ParameterServer
    return [ParameterServer(num_workers=1).start() for _ in range(n)]


def _table(rows, dim, servers, cache_rows, name, optimizer=None):
    from incubator_mxnet_tpu import embedding as mxembed
    return mxembed.ShardedEmbedding(
        name, rows, dim, [("127.0.0.1", s.port) for s in servers],
        seed=17, cache_rows=cache_rows, optimizer=optimizer)


def _train_lane(table, rows, dim, batches=6, bs=32):
    """Module.fit over the over-budget table: the wide-and-deep fixture
    (examples/recommender/wide_deep.py) shrunk to a few batches."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import embedding as mxembed, io, sym
    rng = np.random.RandomState(2)
    n = batches * bs
    ids = rng.randint(0, rows, size=(n, 2)).astype("int64")
    dense = rng.standard_normal((n, 4)).astype("float32")
    label = ((ids[:, 0] + ids[:, 1]) % 2).astype("float32")
    base = io.NDArrayIter({"emb": ids.astype("float32"), "dense": dense},
                          {"softmax_label": label}, batch_size=bs)
    adapter = mxembed.EmbeddingFitAdapter(table, base, id_field=0)
    emb = sym.Variable("emb")
    den = sym.Variable("dense")
    deep = sym.Activation(sym.FullyConnected(emb, num_hidden=8,
                                             name="deep1"),
                          act_type="relu")
    wide = sym.FullyConnected(den, num_hidden=8, name="wide1")
    net = sym.SoftmaxOutput(sym.FullyConnected(deep + wide, num_hidden=2,
                                               name="head"),
                            name="softmax")
    mod = mx.mod.Module(net, data_names=("emb", "dense"),
                        label_names=("softmax_label",), context=mx.cpu())
    mod.bind(data_shapes=adapter.provide_data,
             label_shapes=adapter.provide_label,
             for_training=True, inputs_need_grad=True)
    touched = np.unique(ids)
    before = table.pull_rows(touched)
    t0 = time.time()
    mod.fit(adapter, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            batch_end_callback=adapter.make_callback(mod),
            eval_metric="acc")
    wall = time.time() - t0
    after = table.pull_rows(touched)
    import numpy as _np
    return {
        "batches": batches, "batch_size": bs,
        "pushes": adapter.pushes,
        "rows_trained": (not _np.array_equal(before, after)
                         and bool(_np.isfinite(after).all())),
        "wall_s": round(wall, 3),
    }


def _serve_lane(table, dim, slots=2, n_requests=16):
    """Router fan-out over the over-budget table: results must match a
    direct lookup + forward (the tower sees identical vectors)."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import embedding as mxembed, io, sym
    from incubator_mxnet_tpu.serving import LocalReplica, ReplicaRouter
    np.random.seed(0)
    mx.random.seed(0)
    in_dim = slots * dim
    net = sym.SoftmaxOutput(
        sym.FullyConnected(sym.Variable("emb"), num_hidden=3,
                           name="head"), name="softmax")
    mod = mx.mod.Module(net, data_names=("emb",),
                        label_names=("softmax_label",), context=mx.cpu())
    mod.bind(data_shapes=[io.DataDesc("emb", (2, in_dim))],
             label_shapes=[io.DataDesc("softmax_label", (2,))],
             for_training=False, grad_req="null")
    mod.init_params(mx.initializer.Xavier())
    args, auxs = mod.get_params()
    reps = [LocalReplica(
        mx.serving.ServedModel(net, args, auxs,
                               data_shapes=[("emb", (1, in_dim))],
                               buckets=(1, 2, 4), ctx=mx.cpu(),
                               name="tower"),
        replica_id="r0")]
    rng = np.random.RandomState(3)
    ok = 0
    t0 = time.time()
    with ReplicaRouter(reps, health_interval_s=0.5) as router:
        path = mxembed.EmbeddingServingPath(table, router,
                                            embed_input="emb")
        for _ in range(n_requests):
            ids = rng.randint(0, table.num_rows, size=(2, slots))
            got = path.predict(ids, timeout_ms=10000)[0].asnumpy()
            vecs = table.lookup(ids, out_np=True).reshape(2, in_dim)
            mod.forward(io.DataBatch(
                data=[mx.nd.array(vecs)],
                label=[mx.nd.zeros((2,))]), is_train=False)
            want = mod.get_outputs()[0].asnumpy()
            ok += int(np.allclose(got, want, rtol=1e-5, atol=1e-6))
        st = path.stats()
    return {
        "requests": n_requests, "matched": ok,
        "completed": st["completed"],
        "wall_s": round(time.time() - t0, 3),
        "served_correctly": ok == n_requests
                            and st["completed"] == n_requests,
    }


def _throughput_lanes(table, iters, batch):
    """Cold-pull vs hot-cache rows/s over the SAME table + batch size,
    plus the zero-recompile certificate for the timed hot region."""
    import numpy as np
    from incubator_mxnet_tpu import compile as _compile
    rng = np.random.RandomState(7)
    rows = table.num_rows

    # cold: every batch sweeps fresh ids — all misses, every row over
    # the wire (insert/scatter overhead included, as in production)
    sweep = rng.permutation(rows)[:iters * batch].reshape(iters, batch)
    t0 = time.time()
    for i in range(iters):
        table.lookup(sweep[i])
    cold_s = time.time() - t0
    cold_rps = iters * batch / cold_s

    # hot: one working set, looked up repeatedly — device gathers only
    hot = rng.randint(0, rows, size=batch)
    table.lookup(hot)                     # warm the set + padded shapes
    c0 = _compile.stats()["counters"]["compiles"]
    p0 = table.cache.program_count()
    t0 = time.time()
    for _ in range(iters):
        table.lookup(hot)
    hot_s = time.time() - t0
    hot_rps = iters * batch / hot_s
    compiles = _compile.stats()["counters"]["compiles"] - c0
    programs = table.cache.program_count() - p0

    st = table.cache.stats()
    return {
        "iters": iters, "batch_rows": batch,
        "cold_rows_per_s": round(cold_rps, 1),
        "hot_rows_per_s": round(hot_rps, 1),
        "hot_over_cold": round(hot_rps / cold_rps, 2),
        "cache_hit_rate": round(st["hit_rate"], 3),
        "steady_compiles": compiles,
        "steady_new_programs": programs,
    }


def _latency_lane(table, per_thread, batch, n_threads=4):
    """p50/p99 lookup latency while n_threads hammer concurrently."""
    import numpy as np
    rng = np.random.RandomState(11)
    hot = rng.randint(0, table.num_rows, size=batch)
    table.lookup(hot)
    lat = [[] for _ in range(n_threads)]

    def worker(k):
        for _ in range(per_thread):
            t0 = time.perf_counter()
            table.lookup(hot)
            lat[k].append((time.perf_counter() - t0) * 1e3)

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(n_threads)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.time() - t0
    alll = np.sort(np.concatenate(lat))
    return {
        "threads": n_threads, "lookups": int(alll.size),
        "p50_ms": round(float(np.percentile(alll, 50)), 3),
        "p99_ms": round(float(np.percentile(alll, 99)), 3),
        "lookups_per_s": round(alll.size / wall, 1),
    }


def main(argv=None):
    ap = argparse.ArgumentParser(prog="run_embed_bench",
                                 description=__doc__)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    out_path = args.out if args.out is not None \
        else os.path.join(REPO, "BENCH_EMBED.json")

    import incubator_mxnet_tpu as mx
    t0 = time.time()
    # 70k x 16 fp32 = 4.3 MB >= 4x the 1 MB modeled budget
    rows, dim = (70_000, 16) if not args.quick else (70_000, 16)
    iters, batch = (40, 256) if not args.quick else (10, 256)
    servers = _spawn(2)
    try:
        table = _table(rows, dim, servers, cache_rows=4096, name="bench",
                       optimizer=mx.optimizer.SGD(learning_rate=0.1))
        over = round(table.over_hbm_ratio, 2)
        train = _train_lane(table, rows, dim)
        serve = _serve_lane(table, dim)
        thr = _throughput_lanes(table, iters, batch)
        lat = _latency_lane(table, per_thread=iters // 2, batch=batch)
        stats = table.stats()
        table.close()
    finally:
        for s in servers:
            s.shutdown()

    gates = {
        "table_over_4x_hbm": over >= 4.0,
        "trains_via_fit": train["pushes"] > 0 and train["rows_trained"],
        "serves_via_router": serve["served_correctly"],
        "hot_cache_2x_cold": thr["hot_over_cold"] >= 2.0,
        "zero_steady_recompiles": (thr["steady_compiles"] == 0
                                   and thr["steady_new_programs"] == 0),
        "latency_measured": lat["lookups"] > 0 and lat["p99_ms"] > 0,
    }
    artifact = {
        "config": {"rows": rows, "dim": dim, "shards": len(servers),
                   "cache_rows": 4096, "partition": stats["partition"],
                   "table_mb": round(stats["table_bytes"] / 2**20, 2),
                   "hbm_budget_mb": 1},
        "over_hbm_ratio": over,
        "train": train,
        "serve": serve,
        "throughput": thr,
        "latency": lat,
        "gates": gates,
        "all_passed": all(gates.values()),
        "quick": args.quick,
        "duration_s": round(time.time() - t0, 1),
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(artifact, f, indent=1)
    if args.as_json:
        print(json.dumps(artifact))
    else:
        print("embed bench: over_hbm=%.1fx hot/cold=%.2fx p99=%.2fms "
              "all_passed=%s -> %s" %
              (over, thr["hot_over_cold"], lat["p99_ms"],
               artifact["all_passed"], out_path))
    return 0 if artifact["all_passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
