"""Decompose the fused-path vs pure-JAX-control performance gap on chip.

Phases (select via argv, default all):
  control   — bench.py's hand-written raw-JAX ResNet-50 train step
  module    — public Module.fit fused path (what BENCH measures), then the
              SAME compiled program raw-called in a tight donated loop to
              split host-wrapper overhead from device-program time
  graphsgd  — framework symbol graph (graph_eval_fn) fwd+vjp with a
              hand-written SGD-momentum update: isolates graph quality from
              the traced-optimizer/metric/key epilogue

Prints one JSON line per phase.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BATCH = int(os.environ.get("PD_BATCH", 128))
IMAGE = int(os.environ.get("PD_IMAGE", 224))
STEPS = int(os.environ.get("PD_STEPS", 20))
DTYPE = os.environ.get("PD_DTYPE", "bfloat16")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

cache_dir = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)


def emit(phase, **kw):
    print(json.dumps({"phase": phase, **{k: (round(v, 2) if isinstance(v, float) else v) for k, v in kw.items()}}), flush=True)


def phase_control():
    import bench
    ctl = bench._pure_jax_resnet50(BATCH, IMAGE, DTYPE)
    c_compile, img_s = bench._measure_control(*ctl, STEPS)
    emit("control", compile_s=c_compile, img_s=img_s,
         ms_per_step=1000.0 * BATCH / img_s)


def phase_module():
    import bench
    import incubator_mxnet_tpu as mx

    mx.random.seed(0)
    mod, ctx = bench._build_module(mx, BATCH, IMAGE, DTYPE)
    warm = 2
    it = bench._synthetic_iter(mx, BATCH, IMAGE, DTYPE, warm + STEPS + 1, ctx)
    probe = bench._Probe(warm, STEPS, BATCH)
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "multi_precision": DTYPE != "float32",
                              "rescale_grad": 1.0 / BATCH},
            eval_metric="acc",
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in", magnitude=2),
            batch_end_callback=probe, kvstore=None)
    fused = mod._fused_step
    assert fused is not None and not fused.broken
    emit("module_fit", compile_s=probe.compile_s, img_s=probe.img_s,
         ms_per_step=1000.0 * BATCH / probe.img_s)

    # raw-call the SAME compiled program in a tight donated loop
    carry = fused._carry
    ws, ss, auxs = list(carry[0]), carry[1], list(carry[2])
    mcarry = [tuple(m._device_totals) for _, m in
              fused._metric_leaves(None) or []]
    # rebuild mcarry the way the wrapper does (metric was 'acc')
    import jax.numpy as jnp
    mcarry = [(jax.device_put(jnp.zeros((), jnp.float32), fused._rep_sharding),
               jax.device_put(jnp.zeros((), jnp.int32), fused._rep_sharding))]
    key = fused._key
    t_vec = fused._t_vec
    data = nd_batch_inputs(fused, it, mx)
    fixed = [fused._exec0.arg_dict[n]._data for n in fused._fixed_names]
    lr_dev, wd_dev, rescale_dev = fused._hyper_dev
    jit = fused._jit

    if fused._derive_ws:
        out = jit(tuple(ss), auxs, mcarry, key, t_vec, data, fixed,
                  lr_dev, wd_dev, rescale_dev)
        float(out[3][0][0])   # value fetch = the only reliable barrier
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = jit(out[1], list(out[2]), list(out[3]), out[4], out[5],
                      data, fixed, lr_dev, wd_dev, rescale_dev)
        float(out[3][0][0])
    else:
        out = jit(ws, tuple(ss), auxs, mcarry, key, t_vec, data, fixed,
                  lr_dev, wd_dev, rescale_dev)
        float(out[3][0][0])
        t0 = time.perf_counter()
        for _ in range(STEPS):
            out = jit(list(out[0]), out[1], list(out[2]), list(out[3]),
                      out[4], out[5], data, fixed, lr_dev, wd_dev,
                      rescale_dev)
        float(out[3][0][0])
    dt = time.perf_counter() - t0
    emit("module_rawcall", img_s=BATCH * STEPS / dt,
         ms_per_step=1000.0 * dt / STEPS)


def nd_batch_inputs(fused, it, mx):
    it.reset()
    b = it.next()
    data = list(b.data) + list(b.label or [])
    out = []
    for v, name in zip(data, fused._input_names):
        raw = v._data
        out.append(jax.device_put(raw, fused._data_sharding))
    return out


def phase_graphsgd():
    import bench
    import incubator_mxnet_tpu as mx
    import jax.numpy as jnp
    from incubator_mxnet_tpu.symbol.symbol import graph_eval_fn
    from incubator_mxnet_tpu import sym
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    mx.random.seed(0)
    net = resnet50_v1(classes=1000)
    data_v = sym.Variable("data")
    out = net(data_v)
    out = sym.SoftmaxOutput(out, name="softmax")
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    mod = mx.mod.Module(out, context=ctx, label_names=("softmax_label",))
    from incubator_mxnet_tpu import io
    data_desc = io.DataDesc("data", (BATCH, 3, IMAGE, IMAGE),
                            dtype=np.dtype(DTYPE))
    label_desc = io.DataDesc("softmax_label", (BATCH,), dtype=np.float32)
    mod.bind(data_shapes=[data_desc], label_shapes=[label_desc])
    mod.init_params(mx.initializer.Xavier(rnd_type="gaussian",
                                          factor_type="in", magnitude=2))

    symbol = mod._symbol
    gfn, arg_nodes, aux_nodes, n_rng = graph_eval_fn(symbol, True)
    arg_names = symbol.list_arguments()
    exec0 = mod._exec_group.execs[0]
    param_names = [n for n in mod._exec_group.param_names]
    input_names = mod._exec_group.data_names + mod._exec_group.label_names

    low = DTYPE != "float32"
    # master weights fp32 when low precision; cast inside like control
    w = {}
    for n in param_names:
        a = exec0.arg_dict[n]._data
        w[n] = a.astype(jnp.float32) if low else a
    auxs = [exec0.aux_dict[n]._data for n in symbol.list_auxiliary_states()]
    m = {k: jnp.zeros_like(v) for k, v in w.items()}

    def step(w, m, auxs, data, label, lr):
        def forward(pw):
            args = []
            for n in arg_names:
                if n in pw:
                    args.append(pw[n].astype(DTYPE) if low else pw[n])
                elif n == "data":
                    args.append(data)
                else:
                    args.append(label)
            outs, new_aux = gfn(tuple(args), tuple(auxs), jax.random.PRNGKey(0))
            return tuple(outs), tuple(new_aux)

        outs, vjp, new_aux = jax.vjp(forward, w, has_aux=True)
        cts = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
        (grads,) = vjp(cts)
        new_w, new_m = {}, {}
        for n in w:
            g = grads[n].astype(w[n].dtype) / BATCH
            mom = 0.9 * m[n] - lr * g
            new_m[n] = mom
            new_w[n] = w[n] + mom
        return new_w, new_m, new_aux

    jit = jax.jit(step, donate_argnums=(0, 1, 2))
    data = jax.device_put(
        np.random.rand(BATCH, 3, IMAGE, IMAGE).astype(np.float32),
        ctx.jax_device).astype(DTYPE)
    label = jax.device_put(
        np.random.randint(0, 1000, BATCH).astype(np.float32), ctx.jax_device)
    lr = jnp.float32(0.05)

    # block_until_ready is not a reliable barrier on the tunnel-fronted
    # platform — every window must end with a VALUE fetch (same sync the
    # control and the Module probe use)
    def fetch(w):
        return float(jax.numpy.sum(
            jax.numpy.abs(w[param_names[0]].astype(jax.numpy.float32))))

    t0 = time.perf_counter()
    w, m, auxs = jit(w, m, auxs, data, label, lr)
    fetch(w)
    compile_s = time.perf_counter() - t0
    w, m, auxs = jit(w, m, auxs, data, label, lr)
    fetch(w)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        w, m, auxs = jit(w, m, auxs, data, label, lr)
    chk = fetch(w)
    dt = time.perf_counter() - t0
    assert np.isfinite(chk), f"non-finite weights after {STEPS} steps"
    emit("graph_sgd", compile_s=compile_s, img_s=BATCH * STEPS / dt,
         ms_per_step=1000.0 * dt / STEPS, chk=chk)


def phase_nhwc():
    """bench.py's control rewritten to execute in NHWC (channels-minor):
    input transposed NCHW->NHWC inside the step (API boundary cost paid),
    weights held HWIO, BN/pool over the trailing channel axis.  Measures
    the layout lever against phase_control on the same chip."""
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.RandomState(0)
    params, auxs = {}, {}

    def conv_p(name, cin, cout, k):
        fan = (cin * k * k + cout * k * k) / 2.0
        s = np.sqrt(3.0 / fan)
        params[name + ".w"] = rng.uniform(
            -s, s, (k, k, cin, cout)).astype("f4")  # HWIO

    def bn_p(name, c):
        params[name + ".g"] = np.ones(c, "f4")
        params[name + ".b"] = np.zeros(c, "f4")
        auxs[name + ".mean"] = np.zeros(c, "f4")
        auxs[name + ".var"] = np.ones(c, "f4")

    conv_p("stem", 3, 64, 7)
    bn_p("stem", 64)
    layers = [3, 4, 6, 3]
    chans = [(64, 256), (128, 512), (256, 1024), (512, 2048)]
    cin = 64
    for si, (n, (cm, cout)) in enumerate(zip(layers, chans)):
        for bi in range(n):
            p = f"s{si}b{bi}"
            conv_p(p + ".c1", cin if bi == 0 else cout, cm, 1)
            bn_p(p + ".c1", cm)
            conv_p(p + ".c2", cm, cm, 3)
            bn_p(p + ".c2", cm)
            conv_p(p + ".c3", cm, cout, 1)
            bn_p(p + ".c3", cout)
            if bi == 0:
                conv_p(p + ".ds", cin, cout, 1)
                bn_p(p + ".ds", cout)
        cin = cout
    s = np.sqrt(3.0 / ((2048 + 1000) / 2.0))
    params["fc.w"] = rng.uniform(-s, s, (1000, 2048)).astype("f4")
    params["fc.b"] = np.zeros(1000, "f4")

    def conv(x, w, stride=1):
        return lax.conv_general_dilated(
            x, w.astype(x.dtype), (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def bn(x, p, aux, name, new_aux):
        xm = x.astype(jnp.float32)
        mean = xm.mean((0, 1, 2))
        var = xm.var((0, 1, 2))
        new_aux[name + ".mean"] = 0.9 * aux[name + ".mean"] + 0.1 * mean
        new_aux[name + ".var"] = 0.9 * aux[name + ".var"] + 0.1 * var
        inv = lax.rsqrt(var + 1e-5) * p[name + ".g"]
        out = (xm - mean) * inv + p[name + ".b"]
        return out.astype(x.dtype)

    def forward(p, aux, x):
        new_aux = {}
        x = jnp.transpose(x, (0, 2, 3, 1))  # NCHW API -> NHWC internal
        h = conv(x, p["stem.w"], 2)
        h = jax.nn.relu(bn(h, p, aux, "stem", new_aux))
        h = lax.reduce_window(h, -jnp.inf, lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
        for si, (n, (cm, cout)) in enumerate(zip(layers, chans)):
            for bi in range(n):
                pre = f"s{si}b{bi}"
                stride = 2 if (bi == 0 and si > 0) else 1
                idn = h
                o = jax.nn.relu(bn(conv(h, p[pre + ".c1.w"], stride),
                                   p, aux, pre + ".c1", new_aux))
                o = jax.nn.relu(bn(conv(o, p[pre + ".c2.w"]),
                                   p, aux, pre + ".c2", new_aux))
                o = bn(conv(o, p[pre + ".c3.w"]), p, aux, pre + ".c3",
                       new_aux)
                if bi == 0:
                    idn = bn(conv(h, p[pre + ".ds.w"], stride),
                             p, aux, pre + ".ds", new_aux)
                h = jax.nn.relu(o + idn)
        h = h.mean((1, 2)).astype(jnp.float32)
        return h @ p["fc.w"].astype(jnp.float32).T + p["fc.b"], new_aux

    low = DTYPE != "float32"
    import jax.numpy as jnp2
    w = {k: jnp2.asarray(v) for k, v in params.items()}
    m = {k: jnp2.zeros_like(v) for k, v in w.items()}
    aux = {k: jnp2.asarray(v) for k, v in auxs.items()}

    def loss_fn(w, img, label, aux):
        wl = {k: v.astype(DTYPE) for k, v in w.items()} if low else w
        logits, new_aux = forward(wl, aux, img)
        logp = jax.nn.log_softmax(logits)
        ll = jnp.take_along_axis(logp, label[:, None], -1)
        return -jnp.mean(ll), new_aux

    def train_step(w, m, aux, img, label, lr):
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(w, img, label, aux)
        new_w, new_m = {}, {}
        for n in w:
            g = grads[n].astype(w[n].dtype)
            mom = 0.9 * m[n] - lr * g
            new_m[n] = mom
            new_w[n] = w[n] + mom
        return new_w, new_m, new_aux, loss

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    img = jnp.asarray(np.random.rand(BATCH, 3, IMAGE, IMAGE), DTYPE)
    label = jnp.asarray(np.random.randint(0, 1000, BATCH), jnp.int32)

    import bench
    c_compile, img_s = bench._measure_control(step, w, m, aux, img, label,
                                              STEPS)
    emit("control_nhwc", compile_s=c_compile, img_s=img_s,
         ms_per_step=1000.0 * BATCH / img_s)


if __name__ == "__main__":
    phases = sys.argv[1:] or ["control", "module", "graphsgd"]
    for p in phases:
        globals()["phase_" + p]()
