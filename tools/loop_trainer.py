#!/usr/bin/env python
"""Real-process trainer for the train-to-serve loop chaos schedules.

Trains the same tiny MLP the serving fleet boots with (fc0 64-tanh ->
head 8 -> softmax over 16 features), reading its shard through
`MXRecordIO` — so a configured ``io.corrupt_record`` fault clause
damages REAL record bytes in flight, exactly like a flaky disk — and
publishes guardian-healthy elastic checkpoints into a shared
`ModelRegistry` via `CheckpointPublisher`.  The chaos driver
(run_chaos.py --loop) SIGKILLs, sabotages, and watches this process
from the serving side; the exit report JSON carries the trainer-side
half of the certification (corrupt records detected, guardian
rollbacks, registry fences).

Record format: recordio.pack(IRHeader(0, label, id, 0),
16 float32 features + crc32(features || label || id)).  The crc makes
seeded payload corruption (faults.mutate bit-flips) detectable even
when the recordio framing survives: a damaged record is counted,
skipped, and training continues — the io tier's substitute-and-count
contract.

Usage::

    python tools/loop_trainer.py --registry DIR --ckpt DIR \
        --rec shard.rec --report out.json [--num-epoch 3] \
        [--publish-steps 4] [--checkpoint-period 2]
"""
from __future__ import annotations

import argparse
import json
import os
import struct
import sys
import zlib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

N_FEAT = 16
N_CLASS = 8
_PAYLOAD = struct.Struct("<%df" % N_FEAT)
_CRC = struct.Struct("<I")


def _crc(features_bytes, label, rec_id):
    return zlib.crc32(features_bytes + struct.pack("<fI", float(label),
                                                   int(rec_id)))


def write_shard(path, n=96, seed=11):
    """A learnable shard: class k spikes feature 2k, so a small MLP
    separates the 8 classes in a couple of epochs."""
    import numpy as np
    from incubator_mxnet_tpu import recordio
    rng = np.random.RandomState(seed)
    w = recordio.MXRecordIO(path, "w")
    for i in range(n):
        label = i % N_CLASS
        x = (rng.standard_normal(N_FEAT) * 0.1).astype(np.float32)
        x[label * 2] += 2.0
        body = _PAYLOAD.pack(*x.tolist())
        w.write(recordio.pack(
            recordio.IRHeader(0, float(label), i, 0),
            body + _CRC.pack(_crc(body, label, i))))
    w.close()
    return n


def holdout_batch(k=4, seed=12):
    """(inputs dict, labels) drawn from the same distribution as the
    shard — the serving-side pinned canary slice."""
    import numpy as np
    rng = np.random.RandomState(seed)
    x = (rng.standard_normal((k, N_FEAT)) * 0.1).astype(np.float32)
    labels = np.arange(k) % N_CLASS
    for r, lbl in enumerate(labels):
        x[r, lbl * 2] += 2.0
    return {"data": x}, labels.astype(np.float32)


class RecordFloatIter:
    """Streaming DataIter over the float shard: every epoch re-reads the
    record file through MXRecordIO (the ``io.corrupt_record`` payload
    site), crc-verifies each record, and skips-and-counts damaged ones.
    """

    def __init__(self, path, batch_size=4):
        import numpy as np
        from incubator_mxnet_tpu import io, recordio
        self._np, self._io, self._recordio = np, io, recordio
        self.path = path
        self.batch_size = int(batch_size)
        self.corrupt_records = 0
        self._reader = None
        self._windows = []   # per-batch (lo, hi) record-ordinal windows
        self.reset()

    @property
    def provide_data(self):
        return [self._io.DataDesc("data", (self.batch_size, N_FEAT),
                                  self._np.float32)]

    @property
    def provide_label(self):
        return [self._io.DataDesc("softmax_label", (self.batch_size,),
                                  self._np.float32)]

    def reset(self):
        if self._reader is not None:
            self._reader.close()
        self._reader = self._recordio.MXRecordIO(self.path, "r")
        self._pos = 0          # record ordinal within this epoch
        self._nbatch = 0
        self._windows = []

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def _read_sample(self):
        """(features, label) or None at EOF; damaged records are counted
        and skipped, never delivered."""
        while True:
            raw = self._reader.read()
            if raw is None:
                return None
            self._pos += 1
            try:
                header, blob = self._recordio.unpack(raw)
                body, crc = blob[:_PAYLOAD.size], blob[_PAYLOAD.size:]
                if (len(body) != _PAYLOAD.size or len(crc) != _CRC.size
                        or _CRC.unpack(crc)[0]
                        != _crc(body, header.label, header.id)):
                    raise ValueError("crc mismatch")
            except Exception:
                self.corrupt_records += 1
                continue
            x = self._np.asarray(_PAYLOAD.unpack(body),
                                 dtype=self._np.float32)
            return x, float(header.label)

    def next(self):
        lo = self._pos
        xs, ys = [], []
        while len(xs) < self.batch_size:
            sample = self._read_sample()
            if sample is None:
                break
            xs.append(sample[0])
            ys.append(sample[1])
        if not xs:
            raise StopIteration
        pad = self.batch_size - len(xs)
        while len(xs) < self.batch_size:
            xs.append(xs[-1])
            ys.append(ys[-1])
        self._windows.append((lo, self._pos))
        self._nbatch += 1
        from incubator_mxnet_tpu import nd
        np = self._np
        return self._io.DataBatch(
            data=[nd.array(np.stack(xs))],
            label=[nd.array(np.asarray(ys, np.float32))],
            pad=pad, index=None,
            provide_data=self.provide_data,
            provide_label=self.provide_label)

    def seek(self, nbatch):
        """Rollback-resume positioning: re-walk from the epoch start (the
        corrupt-skip offsets must replay identically)."""
        self.reset()
        for _ in range(int(nbatch)):
            try:
                self.next()
            except StopIteration:
                break

    def checkpoint_state(self):
        return {}

    def set_checkpoint_state(self, state, nbatch=0):
        self.seek(nbatch)

    def record_range(self, nbatch):
        """Guardian/publisher shard attribution: the record-ordinal
        window batch `nbatch` of this epoch consumed."""
        n = int(nbatch)
        if 0 <= n < len(self._windows):
            lo, hi = self._windows[n]
        else:
            lo = n * self.batch_size
            hi = lo + self.batch_size
        return (os.path.basename(self.path), lo, hi)

    def close(self):
        if self._reader is not None:
            self._reader.close()
            self._reader = None


def _build_module():
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import sym
    net = sym.Variable("data")
    net = sym.FullyConnected(net, num_hidden=64, name="fc0")
    net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=N_CLASS, name="head")
    net = sym.SoftmaxOutput(net, name="softmax")
    return mx.mod.Module(net, context=mx.cpu())


def main(argv=None):
    ap = argparse.ArgumentParser(prog="loop_trainer", description=__doc__)
    ap.add_argument("--registry", required=True)
    ap.add_argument("--ckpt", required=True)
    ap.add_argument("--rec", required=True)
    ap.add_argument("--report", required=True)
    ap.add_argument("--num-epoch", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--publish-steps", type=int, default=4)
    ap.add_argument("--checkpoint-period", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--write-shard", type=int, default=0,
                    help="write an N-record shard to --rec first")
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import loop as mxloop
    from incubator_mxnet_tpu.checkpoint.manifest import atomic_write_json
    from incubator_mxnet_tpu.resilience.guardian import \
        TrainingDivergedError

    if args.write_shard:
        write_shard(args.rec, n=args.write_shard)
    np.random.seed(5)
    mx.random.seed(5)
    it = RecordFloatIter(args.rec, batch_size=args.batch_size)
    mod = _build_module()
    registry = mxloop.ModelRegistry(args.registry)
    pub = mxloop.CheckpointPublisher(registry, args.ckpt,
                                     publish_steps=args.publish_steps)
    report = {"completed": False, "diverged": None}
    try:
        pub.fit(mod, it, num_epoch=args.num_epoch, optimizer="sgd",
                optimizer_params={"learning_rate": args.lr},
                eval_metric="acc", initializer=mx.initializer.Xavier(),
                checkpoint_period=args.checkpoint_period)
        report["completed"] = True
    except TrainingDivergedError as exc:
        report["diverged"] = str(exc)
    guardian = getattr(mod, "_guardian", None)
    report.update(
        guardian=guardian.stats() if guardian is not None else None,
        publisher=pub.stats(),
        corrupt_records=it.corrupt_records,
        versions=[r["version"] for r in registry.versions()],
        fences=[list(f) for f in registry.fences()],
    )
    it.close()
    atomic_write_json(args.report, report)
    print(json.dumps(report))
    return 0 if (report["completed"] or report["diverged"]) else 1


if __name__ == "__main__":
    sys.exit(main())
