#!/usr/bin/env python
"""mxtrace — merge per-process telemetry into ONE Perfetto-loadable trace.

Every process of a run appends its finished spans to the shared
``MXNET_OBS_TRACE`` JSONL file (obs/trace.py), its fault events to
``MXNET_FAULTS_LOG``, and its quarantine entries to the guardian's
quarantine file — all through the one line-atomic sink
(obs/jsonl_sink.py).  This tool reads any number of those files (plus
profiler chrome-trace dumps) and writes one chrome-trace JSON where:

* each process is a lane group (pid), each thread a lane (tid), every
  span an ``X`` duration event carrying its trace/span/parent ids;
* every cross-process (and cross-thread) parent->child link gets a
  flow arrow (``s``/``f`` events), so a routed request reads as one
  connected tree from the router's submit, through the transport rpc,
  into the subprocess worker's execute — and a training step from
  ``fit.step`` into the parameter server;
* fault/quarantine JSONL events become instant events in their
  process lane, aligned with the spans they disrupted.

It also verifies span-tree integrity: an **orphan** is a span whose
parent id appears nowhere in the merged set — the broken-propagation
signal the obs CI stage gates to ZERO.

Usage:
    python tools/mxtrace.py SPANS.jsonl [MORE.jsonl ...] \
        [--out merged_trace.json] [--json] [--check]

    --out FILE   write the merged chrome trace (default: merged_trace.json
                 next to the first input; '-' skips writing)
    --json       print the summary as one JSON object
    --check      exit 1 when any orphan span survives the merge
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _flow_id(span_id):
    """Stable 31-bit int for chrome-trace flow binding ids."""
    import zlib
    return zlib.crc32(str(span_id).encode()) & 0x7FFFFFFF


def _tid(thread_name):
    import zlib
    return zlib.crc32(str(thread_name or "main").encode()) & 0xFFFF


def load_inputs(paths):
    """Split input files into (span records, event records, chrome
    events) by sniffing each line/file — span lines carry ``k ==
    'span'``, profiler dumps are JSON objects with ``traceEvents``,
    everything else JSONL-parseable is an event (faults, quarantine,
    tsan dumps are skipped: they are one-line reports, not events)."""
    spans, events, chrome = [], [], []
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            print(f"mxtrace: cannot read {path}: {e}", file=sys.stderr)
            continue
        head = text.lstrip()[:1]
        if head == "{" and '"traceEvents"' in text:
            try:
                chrome.extend(json.loads(text).get("traceEvents", []))
                continue
            except ValueError:
                pass
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            if rec.get("k") == "span":
                spans.append(rec)
            elif "lock_graph" in rec:
                continue   # a tsan dump: a report, not a timeline event
            else:
                events.append(rec)
    return spans, events, chrome


def merge(spans, events=(), chrome=()):
    """Build the chrome trace + integrity summary from loaded records."""
    by_id = {s["sp"]: s for s in spans}
    pids = {}
    out_events = []
    orphans = []
    traces = {}
    for s in spans:
        pid = s.get("pid", 0)
        tid = _tid(s.get("thread"))
        pids.setdefault(pid, set()).add((tid, s.get("thread") or "main"))
        args = dict(s.get("args") or {})
        args.update(trace=s.get("tr"), span=s.get("sp"),
                    parent=s.get("pa"), thread=s.get("thread"))
        out_events.append({"ph": "X", "name": s.get("name", "?"),
                           "cat": s.get("cat", "span"),
                           "ts": s.get("ts", 0),
                           "dur": max(int(s.get("dur", 0)), 1),
                           "pid": pid, "tid": tid, "args": args})
        traces.setdefault(s.get("tr"), []).append(s)
        parent = s.get("pa")
        if parent is None:
            continue
        p = by_id.get(parent)
        if p is None:
            orphans.append(s)
            continue
        if p.get("pid") != pid or _tid(p.get("thread")) != tid:
            # the cross-lane edge: a flow arrow from the parent's span
            # to the child's start — Perfetto draws the connected tree
            fid = _flow_id(s["sp"])
            out_events.append({"ph": "s", "cat": "flow", "name": "tr",
                               "id": fid, "pid": p.get("pid", 0),
                               "tid": _tid(p.get("thread")),
                               "ts": p.get("ts", 0) + 1})
            out_events.append({"ph": "f", "bp": "e", "cat": "flow",
                               "name": "tr", "id": fid, "pid": pid,
                               "tid": tid, "ts": s.get("ts", 0)})
    for ev in events:
        pid = ev.get("pid", 0)
        tid = _tid(ev.get("thread"))
        pids.setdefault(pid, set()).add((tid, ev.get("thread") or "main"))
        name = ev.get("site") or ev.get("event") or ev.get("reason") \
            or "event"
        ts = float(ev.get("time", 0)) * 1e6
        out_events.append({
            "ph": "i", "s": "p", "name": str(name),
            "cat": "fault" if ev.get("event") == "fault" else "event",
            "ts": ts, "pid": pid, "tid": tid,
            "args": {k: v for k, v in ev.items()
                     if isinstance(v, (str, int, float, bool))}})
    # lane naming metadata
    for pid, tids in sorted(pids.items()):
        out_events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "args": {"name": f"process {pid}"}})
        for tid, tname in sorted(tids):
            out_events.append({"ph": "M", "name": "thread_name",
                               "pid": pid, "tid": tid,
                               "args": {"name": tname}})
    out_events.extend(chrome)
    summary = {
        "spans": len(spans),
        "traces": len(traces),
        "processes": len({s.get("pid", 0) for s in spans}) or 0,
        "orphan_spans": len(orphans),
        "orphans": [{"span": s.get("sp"), "name": s.get("name"),
                     "parent": s.get("pa"), "pid": s.get("pid")}
                    for s in orphans[:20]],
        "events": len(events),
    }
    return {"traceEvents": out_events, "displayTimeUnit": "ms"}, summary


def trace_tree(spans, trace_id):
    """{span_id: [child ids]} plus roots for one trace (test helper)."""
    children, roots = {}, []
    ids = {s["sp"] for s in spans if s.get("tr") == trace_id}
    for s in spans:
        if s.get("tr") != trace_id:
            continue
        if s.get("pa") is None or s["pa"] not in ids:
            roots.append(s["sp"])
        else:
            children.setdefault(s["pa"], []).append(s["sp"])
    return {"roots": roots, "children": children}


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxtrace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="+",
                    help="span/fault/quarantine JSONL files and/or "
                         "profiler chrome-trace dumps")
    ap.add_argument("--out", default=None,
                    help="merged chrome-trace output path ('-' skips)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when any orphan span survives")
    args = ap.parse_args(argv)

    spans, events, chrome = load_inputs(args.paths)
    trace, summary = merge(spans, events, chrome)
    out = args.out
    if out is None:
        out = os.path.join(os.path.dirname(os.path.abspath(args.paths[0]))
                           or ".", "merged_trace.json")
    if out != "-":
        with open(out, "w") as f:
            json.dump(trace, f)
        summary["out"] = out
    if args.as_json:
        print(json.dumps(summary, indent=1))
    else:
        print("mxtrace: %d span(s) in %d trace(s) across %d process(es), "
              "%d event(s), %d orphan span(s)%s"
              % (summary["spans"], summary["traces"],
                 summary["processes"], summary["events"],
                 summary["orphan_spans"],
                 f" -> {out}" if out != "-" else ""))
        for o in summary["orphans"]:
            print("  orphan: %(name)s span=%(span)s parent=%(parent)s "
                  "pid=%(pid)s" % o)
    if args.check and summary["orphan_spans"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
