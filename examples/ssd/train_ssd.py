#!/usr/bin/env python
"""SSD-VGG16 training — BASELINE config #5 (reference `example/ssd/train.py`
with `symbol/legacy_vgg16_ssd_300.py`).

Builds the SSD detection head over a VGG16-reduced backbone with
multi-scale anchors, trains with the reference's composite objective
(softmax over classes with hard-negative-friendly ignore masking + smooth
L1 on box offsets, both from `MultiBoxTarget`), and runs `MultiBoxDetection`
NMS decoding for evaluation.  Synthetic box data stands in when no dataset
is on disk (zero-egress image); pass --data-train for a real .rec pack of
packed [cls,x1,y1,x2,y2] labels.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym
from incubator_mxnet_tpu.io import NDArrayIter, DataBatch, DataDesc

logging.basicConfig(level=logging.INFO,
                    format="%(asctime)-15s %(message)s")


def _conv_block(data, name, num_filter, n_convs):
    for i in range(n_convs):
        data = sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                               num_filter=num_filter,
                               name=f"{name}_conv{i}")
        data = sym.Activation(data, act_type="relu")
    return sym.Pooling(data, kernel=(2, 2), stride=(2, 2), pool_type="max",
                       name=f"{name}_pool"), data


def vgg16_reduced(data, small=False):
    """VGG16 body returning the multi-scale feature maps SSD taps
    (reference `symbol/legacy_vgg16_ssd_300.py` conv4_3 + conv7 + extras)."""
    f = 0.25 if small else 1.0
    p1, _ = _conv_block(data, "b1", int(64 * f), 2)
    p2, _ = _conv_block(p1, "b2", int(128 * f), 2)
    p3, _ = _conv_block(p2, "b3", int(256 * f), 3)
    p4, c4 = _conv_block(p3, "b4", int(512 * f), 3)
    p5, _ = _conv_block(p4, "b5", int(512 * f), 3)
    # fc6/fc7 as dilated convs (the "reduced" trick)
    fc6 = sym.Convolution(p5, kernel=(3, 3), pad=(3, 3), dilate=(3, 3),
                          num_filter=int(1024 * f), name="fc6")
    fc6 = sym.Activation(fc6, act_type="relu")
    fc7 = sym.Convolution(fc6, kernel=(1, 1), num_filter=int(1024 * f),
                          name="fc7")
    fc7 = sym.Activation(fc7, act_type="relu")
    # extra feature layers at decreasing resolution
    e1 = sym.Convolution(fc7, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                         num_filter=int(256 * f), name="extra1")
    e1 = sym.Activation(e1, act_type="relu")
    e2 = sym.Convolution(e1, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                         num_filter=int(128 * f), name="extra2")
    e2 = sym.Activation(e2, act_type="relu")
    return [c4, fc7, e1, e2]


def ssd_symbol(num_classes, small=False):
    """SSD head: per-scale anchor priors + class/box conv predictors, the
    MultiBoxTarget training objective, MultiBoxDetection decode."""
    data = sym.Variable("data")
    label = sym.Variable("label")
    feats = vgg16_reduced(data, small=small)
    sizes = [(0.1, 0.14), (0.27, 0.38), (0.54, 0.66), (0.78, 0.9)]
    ratios = [(1.0, 2.0, 0.5)] * 4

    cls_preds, loc_preds, anchors = [], [], []
    for i, (feat, sz, rt) in enumerate(zip(feats, sizes, ratios)):
        na = len(sz) + len(rt) - 1
        cls = sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                              num_filter=na * (num_classes + 1),
                              name=f"cls_pred{i}")
        loc = sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                              num_filter=na * 4, name=f"loc_pred{i}")
        # (B, A*(C+1), H, W) -> (B, H*W*A, C+1) -> flat
        cls = sym.transpose(cls, axes=(0, 2, 3, 1))
        cls_preds.append(sym.Reshape(cls, shape=(0, -1, num_classes + 1)))
        loc = sym.transpose(loc, axes=(0, 2, 3, 1))
        loc_preds.append(sym.Reshape(loc, shape=(0, -1)))
        anchors.append(sym.MultiBoxPrior(feat, sizes=sz, ratios=rt,
                                         clip=True))
    cls_concat = sym.concat(*cls_preds, dim=1)             # (B, N, C+1)
    cls_concat = sym.transpose(cls_concat, axes=(0, 2, 1))  # (B, C+1, N)
    loc_concat = sym.concat(*loc_preds, dim=1)             # (B, N*4)
    anchor_concat = sym.concat(*anchors, dim=1)            # (1, N, 4)

    tmp = sym.MultiBoxTarget(anchor_concat, label, cls_concat,
                             overlap_threshold=0.5,
                             negative_mining_ratio=3,
                             variances=(0.1, 0.1, 0.2, 0.2),
                             name="multibox_target")
    loc_target, loc_mask, cls_target = tmp[0], tmp[1], tmp[2]

    cls_prob = sym.SoftmaxOutput(cls_concat, cls_target,
                                 ignore_label=-1, use_ignore=True,
                                 multi_output=True,
                                 normalization="valid", name="cls_prob")
    loc_diff = loc_mask * (loc_concat - loc_target)
    loc_loss = sym.MakeLoss(sym.smooth_l1(loc_diff, scalar=1.0),
                            grad_scale=1.0, normalization="valid",
                            name="loc_loss")
    det = sym.MultiBoxDetection(cls_prob, loc_concat, anchor_concat,
                                nms_threshold=0.45, force_suppress=False,
                                variances=(0.1, 0.1, 0.2, 0.2),
                                name="detection")
    det = sym.BlockGrad(det)
    return sym.Group([cls_prob, loc_loss, sym.BlockGrad(cls_target), det])


class SyntheticDetIter(NDArrayIter):
    """Images with 1-3 colored rectangles; labels (B, M, 5)."""

    def __init__(self, n, batch_size, image=128, num_classes=3, max_obj=3):
        rng = np.random.RandomState(0)
        X = rng.normal(0, 0.1, (n, 3, image, image)).astype("f4")
        Y = np.full((n, max_obj, 5), -1.0, "f4")
        for i in range(n):
            for j in range(rng.randint(1, max_obj + 1)):
                cls = rng.randint(0, num_classes)
                w, h = rng.uniform(0.2, 0.5, 2)
                x1 = rng.uniform(0, 1 - w)
                y1 = rng.uniform(0, 1 - h)
                Y[i, j] = [cls, x1, y1, x1 + w, y1 + h]
                xa, ya = int(x1 * image), int(y1 * image)
                xb, yb = int((x1 + w) * image), int((y1 + h) * image)
                X[i, cls % 3, ya:yb, xa:xb] += 1.0
        super().__init__(X, Y, batch_size=batch_size, shuffle=True,
                         label_name="label")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--num-classes", type=int, default=3)
    ap.add_argument("--image", type=int, default=128)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--small", action="store_true",
                    help="quarter-width backbone for smoke runs")
    args = ap.parse_args()

    net = ssd_symbol(args.num_classes, small=args.small)
    train = SyntheticDetIter(args.n, args.batch_size, args.image,
                             args.num_classes)
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    mod = mx.mod.Module(net, context=ctx, data_names=("data",),
                        label_names=("label",))

    class MultiBoxMetric(mx.metric.EvalMetric):
        """Cross-entropy + smooth-L1 readout (reference metric.py of the
        ssd example)."""

        def __init__(self):
            super().__init__("MultiBox")
            self.num = 2
            self.reset()

        def reset(self):
            self.sum_ce, self.n_ce = 0.0, 0
            self.sum_l1, self.n_l1 = 0.0, 0

        def update(self, labels, preds):
            cls_prob = preds[0].asnumpy()       # (B, C+1, N)
            loc_loss = preds[1].asnumpy()
            cls_target = preds[2].asnumpy()     # (B, N)
            valid = cls_target >= 0
            idx = np.maximum(cls_target.astype(int), 0)
            b, n = np.indices(idx.shape)
            p = cls_prob[b, idx, n]
            ce = -np.log(np.maximum(p, 1e-12))[valid].sum()
            self.sum_ce += ce
            self.n_ce += int(valid.sum())
            self.sum_l1 += float(loc_loss.sum())
            self.n_l1 += loc_loss.size

        def get(self):
            return (["CrossEntropy", "SmoothL1"],
                    [self.sum_ce / max(1, self.n_ce),
                     self.sum_l1 / max(1, self.n_l1)])

    mod.fit(train, num_epoch=args.num_epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 5e-4,
                              "rescale_grad": 1.0 / args.batch_size},
            initializer=mx.initializer.Xavier(),
            eval_metric=MultiBoxMetric(),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 10))

    # decode detections on one batch to exercise the full inference path
    train.reset()
    batch = next(iter(train))
    mod.forward(batch, is_train=False)
    det = mod.get_outputs()[3].asnumpy()
    kept = (det[:, :, 0] >= 0).sum()
    logging.info("decoded %d detections on a %d-image batch", kept,
                 det.shape[0])


if __name__ == "__main__":
    main()
