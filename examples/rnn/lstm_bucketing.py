#!/usr/bin/env python
"""LSTM language model with bucketing (BASELINE config 4; reference
`example/rnn/bucketing/lstm_bucketing.py`).

Variable-length sentences are grouped into length buckets;
BucketingModule compiles ONE XLA program per bucket — the TPU answer to
dynamic sequence lengths (static shapes per program, shared parameters).

With no corpus on disk (this image has zero egress), a synthetic
power-law corpus stands in for Sherlock Holmes/PTB; pass --data to train
on a real tokenized text file (one sentence per line).
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

logging.basicConfig(level=logging.INFO,
                    format="%(asctime)-15s %(message)s")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import rnn


def synthetic_corpus(n_sentences, vocab_size, rng):
    """Power-law token stream with sentence lengths in [8, 60]."""
    probs = 1.0 / np.arange(1, vocab_size + 1)
    probs /= probs.sum()
    out = []
    for _ in range(n_sentences):
        length = int(rng.randint(8, 60))
        out.append(rng.choice(vocab_size, size=length, p=probs).tolist())
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None, help="tokenized corpus file")
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-hidden", type=int, default=200)
    ap.add_argument("--num-embed", type=int, default=200)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--optimizer", default="sgd")
    ap.add_argument("--mom", type=float, default=0.0)
    ap.add_argument("--wd", type=float, default=1e-5)
    ap.add_argument("--kv-store", default="device")
    ap.add_argument("--buckets", type=int, nargs="+",
                    default=[10, 20, 30, 40, 50, 60])
    ap.add_argument("--vocab-size", type=int, default=1000)
    ap.add_argument("--n-sentences", type=int, default=2000)
    ap.add_argument("--fused", action="store_true",
                    help="use FusedRNNCell (one lax.scan per bucket)")
    args = ap.parse_args()

    rng = np.random.RandomState(0)
    if args.data:
        with open(args.data) as f:
            sentences = [line.split() for line in f if line.strip()]
        coded, vocab = rnn.encode_sentences(sentences)
        vocab_size = len(vocab)
    else:
        coded = synthetic_corpus(args.n_sentences, args.vocab_size, rng)
        vocab_size = args.vocab_size

    train_iter = rnn.BucketSentenceIter(coded, args.batch_size,
                                        buckets=args.buckets,
                                        invalid_label=0)

    if args.fused:
        stack = rnn.FusedRNNCell(args.num_hidden,
                                 num_layers=args.num_layers, mode="lstm")
    else:
        stack = rnn.SequentialRNNCell()
        for i in range(args.num_layers):
            stack.add(rnn.LSTMCell(args.num_hidden, prefix=f"lstm_l{i}_"))

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab_size,
                                 output_dim=args.num_embed, name="embed")
        stack.reset()
        outputs, _ = stack.unroll(seq_len, inputs=embed, merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab_size,
                                     name="pred")
        lab = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, lab, name="softmax")
        return pred, ("data",), ("softmax_label",)

    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    model = mx.mod.BucketingModule(
        sym_gen=sym_gen,
        default_bucket_key=train_iter.default_bucket_key,
        context=ctx)
    model.fit(
        train_data=train_iter,
        eval_metric=mx.metric.Perplexity(0),
        kvstore=args.kv_store,
        optimizer=args.optimizer,
        optimizer_params={"learning_rate": args.lr, "momentum": args.mom,
                          "wd": args.wd,
                          "rescale_grad": 1.0 / args.batch_size},
        initializer=mx.initializer.Xavier(factor_type="in", magnitude=2.34),
        num_epoch=args.num_epochs,
        batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))


if __name__ == "__main__":
    main()
