#!/usr/bin/env python
"""Train ResNet on ImageNet-layout data — BASELINE config #2 (reference
`example/image-classification/train_imagenet.py`).

Feeds from a RecordIO pack (`--data-train .../train.rec`, the reference's
dataset format — the native-indexed multi-threaded `ImageRecordIter`) or a
synthetic corpus when no dataset is on disk (zero-egress image).

TPU-first defaults: bf16 training (`--dtype bfloat16` uses the MXU's
native multiply format), one fused XLA program per step via hybridized
symbols, `kvstore='tpu'` all-reduce for multi-chip.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.io import NDArrayIter

logging.basicConfig(level=logging.INFO,
                    format="%(asctime)-15s %(message)s")


def get_resnet(num_classes, num_layers, image_shape):
    from symbols.resnet import get_symbol
    return get_symbol(num_classes=num_classes, num_layers=num_layers,
                      image_shape=image_shape)


def synthetic_iters(batch_size, image_shape, num_classes, n=512):
    shape = tuple(int(x) for x in image_shape.split(","))
    rng = np.random.RandomState(0)
    X = rng.normal(0, 1, (n,) + shape).astype("f4")
    y = rng.randint(0, num_classes, n).astype("f4")
    return (NDArrayIter(X, y, batch_size=batch_size, shuffle=True),
            NDArrayIter(X[: n // 4], y[: n // 4], batch_size=batch_size))


def rec_iters(args, shape):
    kw = dict(data_shape=shape, batch_size=args.batch_size,
              preprocess_threads=args.data_nthreads,
              mean_r=123.68, mean_g=116.78, mean_b=103.94)
    train = mx.io.ImageRecordIter(path_imgrec=args.data_train, shuffle=True,
                                  rand_crop=True, rand_mirror=True,
                                  resize=256, **kw)
    val = None
    if args.data_val:
        val = mx.io.ImageRecordIter(path_imgrec=args.data_val, resize=256,
                                    **kw)
    return train, val


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-train", default=None, help="train.rec path")
    ap.add_argument("--data-val", default=None, help="val.rec path")
    ap.add_argument("--network", default="resnet")
    ap.add_argument("--num-layers", type=int, default=50)
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-epochs", type=int, default=1)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--mom", type=float, default=0.9)
    ap.add_argument("--wd", type=float, default=1e-4)
    ap.add_argument("--kv-store", default="device")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16", "float16"])
    ap.add_argument("--data-nthreads", type=int, default=4)
    ap.add_argument("--disp-batches", type=int, default=20)
    ap.add_argument("--model-prefix", default=None)
    ap.add_argument("--synthetic-n", type=int, default=512)
    args = ap.parse_args()

    shape = tuple(int(x) for x in args.image_shape.split(","))
    net = get_resnet(args.num_classes, args.num_layers, args.image_shape)

    if args.data_train:
        train, val = rec_iters(args, shape)
    else:
        logging.info("no --data-train: running on synthetic data")
        train, val = synthetic_iters(args.batch_size, args.image_shape,
                                     args.num_classes, args.synthetic_n)

    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    mod = mx.mod.Module(net, context=ctx)
    checkpoint = (mx.callback.do_checkpoint(args.model_prefix)
                  if args.model_prefix else None)
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            kvstore=args.kv_store, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr,
                              "momentum": args.mom, "wd": args.wd,
                              "rescale_grad": 1.0 / args.batch_size,
                              "multi_precision":
                                  args.dtype != "float32"},
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in",
                                              magnitude=2),
            eval_metric=["accuracy",
                         mx.metric.TopKAccuracy(top_k=5)],
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches),
            epoch_end_callback=checkpoint)


if __name__ == "__main__":
    main()
