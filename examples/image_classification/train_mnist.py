"""Train LeNet/MLP on MNIST via mx.mod.Module — BASELINE config #1
(reference `example/image-classification/train_mnist.py`).

Uses real MNIST idx files when --data-dir has them; otherwise falls back to
the deterministic synthetic MNIST stand-in (zero-egress environment).
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym
from incubator_mxnet_tpu.io import NDArrayIter, MNISTIter


def get_mlp():
    data = sym.Variable("data")
    data = sym.Flatten(data)
    fc1 = sym.FullyConnected(data, name="fc1", num_hidden=128)
    act1 = sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = sym.FullyConnected(act1, name="fc2", num_hidden=64)
    act2 = sym.Activation(fc2, name="relu2", act_type="relu")
    fc3 = sym.FullyConnected(act2, name="fc3", num_hidden=10)
    return sym.SoftmaxOutput(fc3, name="softmax")


def get_lenet():
    data = sym.Variable("data")
    conv1 = sym.Convolution(data, kernel=(5, 5), num_filter=20)
    tanh1 = sym.Activation(conv1, act_type="tanh")
    pool1 = sym.Pooling(tanh1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    conv2 = sym.Convolution(pool1, kernel=(5, 5), num_filter=50)
    tanh2 = sym.Activation(conv2, act_type="tanh")
    pool2 = sym.Pooling(tanh2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    flatten = sym.Flatten(pool2)
    fc1 = sym.FullyConnected(flatten, num_hidden=500)
    tanh3 = sym.Activation(fc1, act_type="tanh")
    fc2 = sym.FullyConnected(tanh3, num_hidden=10)
    return sym.SoftmaxOutput(fc2, name="softmax")


def get_iters(args):
    img = os.path.join(args.data_dir, "train-images-idx3-ubyte")
    if os.path.exists(img) or os.path.exists(img + ".gz"):
        train = MNISTIter(image=img,
                          label=os.path.join(args.data_dir,
                                             "train-labels-idx1-ubyte"),
                          batch_size=args.batch_size, shuffle=True)
        val = MNISTIter(image=os.path.join(args.data_dir,
                                           "t10k-images-idx3-ubyte"),
                        label=os.path.join(args.data_dir,
                                           "t10k-labels-idx1-ubyte"),
                        batch_size=args.batch_size, shuffle=False)
        return train, val
    logging.warning("MNIST files not found in %s; using synthetic data",
                    args.data_dir)
    from incubator_mxnet_tpu.test_utils import get_mnist_like
    X, y = get_mnist_like(4096)
    train = NDArrayIter(X[:3584], y[:3584], args.batch_size, shuffle=True)
    val = NDArrayIter(X[3584:], y[3584:], args.batch_size)
    return train, val


def main():
    parser = argparse.ArgumentParser(description="train mnist")
    parser.add_argument("--network", default="mlp", choices=["mlp", "lenet"])
    parser.add_argument("--data-dir", default="data/mnist/")
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--kv-store", default="local")
    parser.add_argument("--gpus", default=None,
                        help="comma-separated accelerator ids, e.g. 0 or 0,1")
    parser.add_argument("--model-prefix", default=None)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    net = get_mlp() if args.network == "mlp" else get_lenet()
    train, val = get_iters(args)
    if args.gpus:
        ctx = [mx.tpu(int(i)) for i in args.gpus.split(",")]
    else:
        ctx = mx.cpu()
    mod = mx.mod.Module(net, context=ctx)
    cb = [mx.callback.Speedometer(args.batch_size, 50)]
    ep = [mx.callback.do_checkpoint(args.model_prefix)] \
        if args.model_prefix else None
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr,
                              "momentum": args.momentum},
            initializer=mx.initializer.Xavier(),
            kvstore=args.kv_store,
            num_epoch=args.num_epochs,
            batch_end_callback=cb, epoch_end_callback=ep)
    score = mod.score(val, "acc")
    print("final validation accuracy:", score[0][1])
    return score[0][1]


if __name__ == "__main__":
    main()
