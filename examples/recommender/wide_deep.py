#!/usr/bin/env python
"""Wide-and-deep recommender on the sharded sparse-embedding tier.

The workload the source framework was famous for: a click-through model
whose user/item embedding table is too big for one device's HBM.  The
table lives as row shards on `dist_async` parameter-server processes
(`embedding.ShardedEmbedding`); the dense tower is a plain `Module`
trained with `Module.fit` — the guardian, the h2d staging ring and the
checkpoint plane all ride along.  Each batch:

1. the `EmbeddingFitAdapter` looks the batch's ids up (hot rows gather
   straight from the device-resident LRU cache, cold rows pull from
   their shards) and feeds the vectors as a DATA input;
2. the module steps the dense tower; binding with
   ``inputs_need_grad=True`` makes the backward pass leave
   d(loss)/d(vectors) in `get_input_grads`;
3. the batch-end callback pushes that gradient ROW-SPARSE to the owning
   shards, where the lazy optimizer updates only the touched rows.

With no click logs on disk (this image has zero egress), a synthetic
power-law id stream stands in for a production log.  Serving: the same
table fans request id-sets out in front of a `ReplicaRouter` tower
fleet — see `embedding.EmbeddingServingPath`.

Usage:
    python examples/recommender/wide_deep.py [--rows 200000] [--dim 16]
        [--shards 2] [--epochs 2] [--batch-size 64]
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

import numpy as np

logging.basicConfig(level=logging.INFO,
                    format="%(asctime)-15s %(message)s")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import embedding as mxembed
from incubator_mxnet_tpu import io


SLOTS = 2   # (user id, item id)


def synthetic_clicks(n, num_rows, rng):
    """Power-law (user, item) pairs + a planted preference rule."""
    probs = 1.0 / np.arange(1, num_rows + 1) ** 1.1
    probs /= probs.sum()
    ids = rng.choice(num_rows, size=(n, SLOTS), p=probs).astype(np.int64)
    dense = rng.randn(n, 4).astype(np.float32)
    label = ((ids[:, 0] + ids[:, 1]) % 3 == 0).astype(np.float32)
    return ids, dense, label


def tower(embed_width, dense_width, hidden=32):
    """Wide (linear over dense) + deep (MLP over embeddings) tower."""
    emb = mx.sym.Variable("emb")          # looked-up embedding vectors
    den = mx.sym.Variable("dense")
    deep = mx.sym.FullyConnected(emb, num_hidden=hidden, name="deep1")
    deep = mx.sym.Activation(deep, act_type="relu")
    wide = mx.sym.FullyConnected(den, num_hidden=hidden, name="wide1")
    both = deep + wide
    out = mx.sym.FullyConnected(both, num_hidden=2, name="head")
    return mx.sym.SoftmaxOutput(out, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--shards", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--samples", type=int, default=4096)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()

    from incubator_mxnet_tpu.dist.server import ParameterServer
    servers = [ParameterServer(num_workers=1).start()
               for _ in range(args.shards)]
    table = mxembed.ShardedEmbedding(
        "user_item", args.rows, args.dim,
        [("127.0.0.1", s.port) for s in servers], seed=7,
        # SoftmaxOutput grads arrive batch-SUMMED (normalization=
        # 'null'): rescale here or the effective lr is batch_size x
        optimizer=mx.optimizer.SGD(learning_rate=args.lr,
                                   rescale_grad=1.0 / args.batch_size))
    logging.info("table %dx%d = %.1f MB over %d shards (%.1fx the "
                 "modeled HBM budget)", args.rows, args.dim,
                 table.table_bytes / 2**20, table.num_shards,
                 table.over_hbm_ratio)

    rng = np.random.RandomState(0)
    ids, dense, label = synthetic_clicks(args.samples, args.rows, rng)
    base = io.NDArrayIter({"emb": ids.astype(np.float32), "dense": dense},
                          {"softmax_label": label},
                          batch_size=args.batch_size)
    adapter = mxembed.EmbeddingFitAdapter(table, base, id_field=0)

    mod = mx.mod.Module(tower(SLOTS * args.dim, 4),
                        data_names=("emb", "dense"),
                        label_names=("softmax_label",),
                        context=mx.cpu())
    # inputs_need_grad: the backward pass must produce d(loss)/d(emb) —
    # that gradient IS the row-sparse embedding gradient we push
    mod.bind(data_shapes=adapter.provide_data,
             label_shapes=adapter.provide_label,
             for_training=True, inputs_need_grad=True)
    mod.fit(adapter, num_epoch=args.epochs,
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr,
                              "rescale_grad": 1.0 / args.batch_size},
            batch_end_callback=adapter.make_callback(mod),
            eval_metric="acc")

    stats = table.stats()
    logging.info("pushes=%d lookups=%d hit_rate=%.2f shards=%s",
                 adapter.pushes, stats["lookups"],
                 stats["cache"]["hit_rate"],
                 [(s["rows_pushed"], s["rows_pulled"])
                  for s in stats["shards"].values()])
    table.close()
    for s in servers:
        s.shutdown()


if __name__ == "__main__":
    main()
