"""Benchmark: ResNet-50 training throughput (img/sec) on one chip, driven
through the PUBLIC `Module.fit` API.

Baseline (BASELINE.md): reference MXNet ResNet-50 *training* at 363.69
img/sec on V100, batch 128 (`docs/faq/perf.md:205-224`).

What is measured: `mx.mod.Module.fit` — the same user-facing loop as the
reference's `train_imagenet.py` — with a synthetic device-resident
ImageNet-shaped iterator (the reference perf harness
`benchmark_score.py` uses synthetic data the same way).  `Module.fit`
compiles the whole train step (forward + backward + SGD-momentum +
BatchNorm stats + in-graph accuracy metric) into ONE donated XLA program
per signature (`incubator_mxnet_tpu/fused.py`); nothing here hand-builds
jax — the framework path IS the benched path.

Default dtype is **bfloat16** (the TPU MXU's native matmul type) with
fp32 master weights via the multi-precision optimizer; fp32 is kept as a
lane.  A hand-written pure-JAX ResNet-50 control runs at both dtypes on
the same chip: `ratio_vs_pure_jax` / `ratio_vs_pure_jax_bf16` are the
honest framework-overhead metrics (this environment's chip sits behind an
experimental tunnel, so absolute V100-class numbers are not the point).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
A SIGALRM watchdog (BENCH_BUDGET_S, default 480 s) emits a partial result
instead of dying silently.

Env overrides: BENCH_BATCH (128), BENCH_IMAGE (224), BENCH_STEPS (48),
BENCH_DTYPE (bfloat16), BENCH_BUDGET_S (480), BENCH_CONTROL (1),
BENCH_FP32 (1), BENCH_REAL_DATA (1).

The fit loop runs K steps per dispatch (MXNET_FUSED_STEP_BLOCK, default
8) as one lax.scan program; callbacks fire in bursts of K after each
block, so the probe's warm-up and measurement window are sized to block
boundaries (warm = K, steps rounded up to a K multiple) — the metric
get() at each edge is a true device sync either way.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import time

import numpy as np

BASELINE_IMG_S = 363.69  # reference ResNet-50 training, V100 bs=128

# fit-loop dispatch block size: probe windows align to block boundaries
_BLOCK = max(int(os.environ.get("MXNET_FUSED_STEP_BLOCK", "8") or 1), 1)

_RESULT = {
    "metric": "resnet50_train_img_per_sec",
    "value": 0.0,
    "unit": "img/sec/chip",
    "vs_baseline": 0.0,
    "phase": "startup",
}
_EMITTED = False


def _emit():
    global _EMITTED
    if _EMITTED:
        return
    _EMITTED = True
    print(json.dumps(_RESULT), flush=True)


def _alarm(signum, frame):
    _RESULT["partial"] = True
    _emit()
    os._exit(0)


def _watchdog(budget):
    """Thread-based budget watchdog: SIGALRM delivery is deferred while the
    main thread sits in a long C call (XLA compile over the device tunnel),
    so a timer thread emits the partial result and exits the process."""
    import threading

    def fire():
        _RESULT["partial"] = True
        _emit()
        os._exit(0)

    t = threading.Timer(budget, fire)
    t.daemon = True
    t.start()
    return t


# ---------------------------------------------------------------------------
# Framework path: public Module.fit over a synthetic device-resident iter
# ---------------------------------------------------------------------------

def _synthetic_iter(mx, batch, image, dtype, n_batches, ctx):
    """DataIter yielding the SAME device-resident batch (the reference
    benchmark harness pattern: measure compute, not host data generation)."""
    from incubator_mxnet_tpu import io, nd

    data = nd.array(np.random.rand(batch, 3, image, image).astype("f4"),
                    ctx=ctx).astype(dtype)
    label = nd.array(np.random.randint(0, 1000, batch).astype("f4"), ctx=ctx)
    data_desc = io.DataDesc("data", (batch, 3, image, image),
                            dtype=np.dtype(dtype))
    label_desc = io.DataDesc("softmax_label", (batch,), dtype=np.float32)
    batch_obj = io.DataBatch(data=[data], label=[label], pad=0,
                             provide_data=[data_desc],
                             provide_label=[label_desc])

    class SyntheticIter(io.DataIter):
        def __init__(self):
            super().__init__(batch_size=batch)
            self._i = 0

        @property
        def provide_data(self):
            return [data_desc]

        @property
        def provide_label(self):
            return [label_desc]

        def reset(self):
            self._i = 0

        def next(self):
            if self._i >= n_batches:
                raise StopIteration
            self._i += 1
            return batch_obj

    return SyntheticIter()


class _Probe:
    """Speedometer-style batch callback: syncs on the in-graph metric at
    the window edges and derives steady-state img/s."""

    def __init__(self, warm, steps, batch):
        self.warm = warm
        self.steps = steps
        self.batch = batch
        self.t0 = None
        self.img_s = None
        self.compile_s = None
        self._t_start = time.perf_counter()

    def __call__(self, param):
        if param.nbatch == 0:
            # first batch completed -> compile + first step
            param.eval_metric.get()
            self.compile_s = time.perf_counter() - self._t_start
        elif param.nbatch == self.warm:
            param.eval_metric.get()  # blocks until step `warm` is done
            self.t0 = time.perf_counter()
        elif param.nbatch == self.warm + self.steps:
            acc = dict(param.eval_metric.get_name_value())
            dt = time.perf_counter() - self.t0
            self.img_s = self.batch * self.steps / dt
            self.final_acc = acc


def _build_module(mx, batch, image, dtype, norm=None):
    from incubator_mxnet_tpu import sym
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    net = resnet50_v1(classes=1000)
    data = sym.Variable("data")
    # device-augment pipelines ship uint8 NHWC; `norm` is the in-graph
    # normalize/cast/NCHW head (iterator.normalize_symbol) XLA fuses into
    # the first convolution
    x = norm(data) if norm is not None else data
    out = net(x)  # gluon block composed symbolically
    out = sym.SoftmaxOutput(out, name="softmax")
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    return mx.mod.Module(out, context=ctx,
                         label_names=("softmax_label",)), ctx


def _run_framework(batch, image, steps, dtype):
    import incubator_mxnet_tpu as mx

    mx.random.seed(0)
    t0 = time.perf_counter()
    mod, ctx = _build_module(mx, batch, image, dtype)
    warm = _BLOCK
    # last probe edge (warm+steps) must land inside a full block: feed
    # exactly one block past it, no ragged tail
    it = _synthetic_iter(mx, batch, image, dtype, warm + steps + _BLOCK, ctx)
    probe = _Probe(warm, steps, batch)
    init_s = time.perf_counter() - t0

    mod.fit(it, num_epoch=1,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "multi_precision": dtype != "float32",
                              "rescale_grad": 1.0 / batch},
            eval_metric="acc",
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in", magnitude=2),
            batch_end_callback=probe,
            kvstore=None)
    assert probe.img_s is not None, "probe never hit the measurement window"
    acc = probe.final_acc.get("accuracy", float("nan"))
    assert np.isfinite(acc), "training produced non-finite metric"
    fused = mod._fused_step
    assert fused is not None and not fused.broken, \
        "public fit path must run the fused train step"
    return init_s, probe.compile_s, probe.img_s, fused.compile_phase_stats()


def _run_gluon(batch, image, steps, dtype):
    """Gluon lane: model_zoo ResNet-50 driven by the PUBLIC
    `gluon.contrib.estimator.Estimator.fit` loop — the fused Gluon step
    (gluon/fused_step.py) compiles forward+loss+backward+optimizer+metric
    into one donated program, the Gluon analogue of the Module lane."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, nd
    import jax

    mx.random.seed(0)
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    net = gluon.model_zoo.vision.resnet50_v1(classes=1000)
    net.initialize(mx.initializer.Xavier(rnd_type="gaussian",
                                         factor_type="in", magnitude=2),
                   ctx=ctx)
    if dtype != "float32":
        net.cast(dtype)
    # materialize deferred params with one eager forward so the FIRST fit
    # batch can fuse (otherwise batch 0 runs eager and the probe's
    # compile_s would record the eager step, not the fused XLA compile)
    net(nd.array(np.zeros((1, 3, image, image), "f4"), ctx=ctx).astype(dtype))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9,
                             "multi_precision": dtype != "float32",
                             "rescale_grad": 1.0 / batch})
    est = gluon.contrib.estimator.Estimator(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        train_metrics=[mx.metric.Accuracy()], trainer=trainer)

    data = nd.array(np.random.rand(batch, 3, image, image).astype("f4"),
                    ctx=ctx).astype(dtype)
    label = nd.array(np.random.randint(0, 1000, batch).astype("f4"), ctx=ctx)
    warm = _BLOCK
    times = {}

    class Probe:
        def train_begin(self, est):
            self.t0 = time.perf_counter()

        def epoch_begin(self, est):
            pass

        def batch_begin(self, est):
            pass

        def batch_end(self, est):
            i = est.batch_idx
            if i == 0:
                for m in est.train_metrics:
                    m.get()          # sync: compile + first step done
                times["compile"] = time.perf_counter() - self.t0
            elif i == warm:
                for m in est.train_metrics:
                    m.get()
                times["t0"] = time.perf_counter()
            elif i == warm + steps:
                for m in est.train_metrics:
                    m.get()
                times["img_s"] = batch * steps / (
                    time.perf_counter() - times["t0"])

        def epoch_end(self, est):
            pass

        def train_end(self, est):
            pass

    batches = [(data, label)] * (warm + steps + _BLOCK)
    est.fit(iter(batches), epochs=1, event_handlers=[Probe()])
    assert est._fused is not None and not est._fused.broken, \
        "Estimator must run the fused Gluon step"
    assert "img_s" in times, "gluon probe missed its window"
    return times["compile"], times["img_s"], \
        est._fused.compile_phase_stats()


# ---------------------------------------------------------------------------
# PTB LSTM lane (BASELINE config #4: example/rnn/bucketing/lstm_bucketing.py
# — 2x200 LSTM, embed 200, vocab 10k, batch 32, bptt 35).  The framework
# path is the bucketing example's symbol: cell unroll emits ONE _foreach
# (lax.scan); a hand-written raw-JAX LSTM control runs the same math.
# ---------------------------------------------------------------------------

_LSTM_CFG = dict(vocab=10000, embed=200, hidden=200, layers=2,
                 batch=32, seq=35)


def _lstm_symbol(mx, cfg):
    from incubator_mxnet_tpu import rnn
    stack = rnn.SequentialRNNCell()
    for i in range(cfg["layers"]):
        stack.add(rnn.LSTMCell(cfg["hidden"], prefix=f"lstm_l{i}_"))
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=cfg["vocab"],
                             output_dim=cfg["embed"], name="embed")
    stack.reset()
    outputs, _ = stack.unroll(cfg["seq"], inputs=embed, merge_outputs=True)
    pred = mx.sym.Reshape(outputs, shape=(-1, cfg["hidden"]))
    pred = mx.sym.FullyConnected(pred, num_hidden=cfg["vocab"], name="pred")
    lab = mx.sym.Reshape(label, shape=(-1,))
    net = mx.sym.SoftmaxOutput(pred, lab, name="softmax")
    n_scan = sum(1 for n in net._topo()
                 if not n.is_variable and n.op.name == "_foreach")
    assert n_scan == 1, "bucketed LSTM must compile to ONE scan"
    return net


def _run_lstm_framework(steps):
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import io, nd

    cfg = _LSTM_CFG
    mx.random.seed(0)
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    net = _lstm_symbol(mx, cfg)
    batch, seq = cfg["batch"], cfg["seq"]
    rng = np.random.RandomState(0)
    data = nd.array(rng.randint(0, cfg["vocab"], (batch, seq))
                    .astype("f4"), ctx=ctx)
    label = nd.array(rng.randint(0, cfg["vocab"], (batch, seq))
                     .astype("f4"), ctx=ctx)
    warm = _BLOCK
    n_batches = warm + steps + _BLOCK
    batch_obj = io.DataBatch(
        data=[data], label=[label], pad=0,
        provide_data=[io.DataDesc("data", (batch, seq), dtype=np.float32)],
        provide_label=[io.DataDesc("softmax_label", (batch, seq),
                                   dtype=np.float32)])

    class It(io.DataIter):
        def __init__(self):
            super().__init__(batch_size=batch)
            self._i = 0

        provide_data = property(lambda s: batch_obj.provide_data)
        provide_label = property(lambda s: batch_obj.provide_label)

        def reset(self):
            self._i = 0

        def next(self):
            if self._i >= n_batches:
                raise StopIteration
            self._i += 1
            return batch_obj

    mod = mx.mod.Module(net, context=ctx)
    probe = _Probe(warm, steps, batch)
    mod.fit(It(), num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1.0 / batch},
            eval_metric=mx.metric.Perplexity(0),
            initializer=mx.initializer.Xavier(factor_type="in",
                                              magnitude=2.34),
            batch_end_callback=probe, kvstore=None)
    assert probe.img_s is not None, "lstm probe missed its window"
    fused = mod._fused_step
    assert fused is not None and not fused.broken, \
        "lstm lane must run the fused train step"
    return (probe.compile_s, probe.img_s * seq,   # tokens/s
            fused.compile_phase_stats())


def _pure_jax_lstm(steps):
    """Raw-JAX 2-layer LSTM LM matching _LSTM_CFG: embed -> scan -> FC ->
    CE, SGD momentum, donated step — the hand-written control."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    cfg = _LSTM_CFG
    V, E, H, L = cfg["vocab"], cfg["embed"], cfg["hidden"], cfg["layers"]
    B, T = cfg["batch"], cfg["seq"]
    rng = np.random.RandomState(0)

    def mk(shape, scale=0.1):
        return rng.uniform(-scale, scale, shape).astype("f4")

    w = {"emb": mk((V, E)), "fc_w": mk((V, H)), "fc_b": np.zeros(V, "f4")}
    for i in range(L):
        cin = E if i == 0 else H
        w[f"wx{i}"] = mk((4 * H, cin))
        w[f"wh{i}"] = mk((4 * H, H))
        w[f"b{i}"] = np.zeros(4 * H, "f4")

    def lstm_layer(p, i, xs):
        def step(carry, x):
            h, c = carry
            g = x @ p[f"wx{i}"].T + h @ p[f"wh{i}"].T + p[f"b{i}"]
            ii, f, gg, o = jnp.split(g, 4, axis=-1)
            c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(ii) * jnp.tanh(gg)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (h, c), h

        h0 = jnp.zeros((xs.shape[1], H), xs.dtype)
        (_, _), ys = lax.scan(step, (h0, h0), xs)
        return ys

    def loss_fn(p, tok, lab):
        xs = p["emb"][tok].transpose(1, 0, 2)   # (T, B, E)
        for i in range(L):
            xs = lstm_layer(p, i, xs)
        logits = xs.reshape(-1, H) @ p["fc_w"].T + p["fc_b"]
        logp = jax.nn.log_softmax(logits)
        ll = jnp.take_along_axis(
            logp, lab.transpose(1, 0).reshape(-1)[:, None], -1)
        return -jnp.mean(ll)

    def train_step(p, m, tok, lab, lr):
        loss, grads = jax.value_and_grad(loss_fn)(p, tok, lab)
        new_p, new_m = {}, {}
        for k in p:
            mom = 0.9 * m[k] - lr * grads[k]
            new_m[k] = mom
            new_p[k] = p[k] + mom
        return new_p, new_m, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))
    p = {k: jnp.asarray(v) for k, v in w.items()}
    m = {k: jnp.zeros_like(v) for v, k in zip(w.values(), w)}
    tok = jnp.asarray(rng.randint(0, V, (B, T)), jnp.int32)
    lab = jnp.asarray(rng.randint(0, V, (B, T)), jnp.int32)
    lr = jnp.float32(0.1)
    t0 = time.perf_counter()
    p, m, loss = step(p, m, tok, lab, lr)
    float(loss)
    compile_s = time.perf_counter() - t0
    p, m, loss = step(p, m, tok, lab, lr)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        p, m, loss = step(p, m, tok, lab, lr)
    final = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final)
    return compile_s, B * T * steps / dt


# ---------------------------------------------------------------------------
# Control path: hand-written raw-JAX ResNet-50 train step (no framework)
# ---------------------------------------------------------------------------

def _pure_jax_resnet50(batch, image, dtype):
    """Raw-JAX ResNet-50 v1 (NCHW, same arch as the framework model):
    conv/bn/relu stem, bottleneck stages [3,4,6,3], SGD momentum, BN
    running stats — everything a performance-minded JAX user would write."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.RandomState(0)
    params, auxs = {}, {}

    def conv_p(name, cin, cout, k):
        fan = (cin * k * k + cout * k * k) / 2.0
        s = np.sqrt(3.0 / fan)
        params[name + ".w"] = rng.uniform(-s, s, (cout, cin, k, k)).astype("f4")

    def bn_p(name, c):
        params[name + ".g"] = np.ones(c, "f4")
        params[name + ".b"] = np.zeros(c, "f4")
        auxs[name + ".mean"] = np.zeros(c, "f4")
        auxs[name + ".var"] = np.ones(c, "f4")

    # stem
    conv_p("stem", 3, 64, 7)
    bn_p("stem", 64)
    layers = [3, 4, 6, 3]
    chans = [(64, 256), (128, 512), (256, 1024), (512, 2048)]
    cin = 64
    for si, (n, (cm, cout)) in enumerate(zip(layers, chans)):
        for bi in range(n):
            p = f"s{si}b{bi}"
            conv_p(p + ".c1", cin if bi == 0 else cout, cm, 1)
            bn_p(p + ".c1", cm)
            conv_p(p + ".c2", cm, cm, 3)
            bn_p(p + ".c2", cm)
            conv_p(p + ".c3", cm, cout, 1)
            bn_p(p + ".c3", cout)
            if bi == 0:
                conv_p(p + ".ds", cin, cout, 1)
                bn_p(p + ".ds", cout)
        cin = cout
    s = np.sqrt(3.0 / ((2048 + 1000) / 2.0))
    params["fc.w"] = rng.uniform(-s, s, (1000, 2048)).astype("f4")
    params["fc.b"] = np.zeros(1000, "f4")

    def conv(x, w, stride=1):
        return lax.conv_general_dilated(
            x, w.astype(x.dtype), (stride, stride), "SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    def bn(x, p, aux, name, new_aux):
        xm = x.astype(jnp.float32)
        mean = xm.mean((0, 2, 3))
        var = xm.var((0, 2, 3))
        new_aux[name + ".mean"] = 0.9 * aux[name + ".mean"] + 0.1 * mean
        new_aux[name + ".var"] = 0.9 * aux[name + ".var"] + 0.1 * var
        inv = jax.lax.rsqrt(var + 1e-5) * p[name + ".g"]
        out = (xm - mean[:, None, None]) * inv[:, None, None] + \
            p[name + ".b"][:, None, None]
        return out.astype(x.dtype)

    def forward(p, aux, x):
        new_aux = {}
        h = conv(x, p["stem.w"], 2)
        h = jax.nn.relu(bn(h, p, aux, "stem", new_aux))
        h = lax.reduce_window(h, -jnp.inf, lax.max, (1, 1, 3, 3),
                              (1, 1, 2, 2), "SAME")
        for si, (n, (cm, cout)) in enumerate(zip(layers, chans)):
            for bi in range(n):
                pre = f"s{si}b{bi}"
                stride = 2 if (bi == 0 and si > 0) else 1
                idn = h
                o = jax.nn.relu(bn(conv(h, p[pre + ".c1.w"], stride),
                                   p, aux, pre + ".c1", new_aux))
                o = jax.nn.relu(bn(conv(o, p[pre + ".c2.w"]),
                                   p, aux, pre + ".c2", new_aux))
                o = bn(conv(o, p[pre + ".c3.w"]), p, aux, pre + ".c3", new_aux)
                if bi == 0:
                    idn = bn(conv(h, p[pre + ".ds.w"], stride),
                             p, aux, pre + ".ds", new_aux)
                h = jax.nn.relu(o + idn)
        h = h.mean((2, 3)).astype(jnp.float32)
        return h @ p["fc.w"].astype(jnp.float32).T + p["fc.b"], new_aux

    # master weights and momentum stay fp32; low-precision lanes cast the
    # weights to `dtype` inside the step (exactly the framework's
    # multi-precision semantics, so the ratio compares equal work)
    low = dtype != "float32"
    w = {k: jnp.asarray(v) for k, v in params.items()}
    m = {k: jnp.zeros_like(v) for k, v in w.items()}
    aux = {k: jnp.asarray(v) for k, v in auxs.items()}

    def loss_fn(w, img, label, aux):
        wl = {k: v.astype(dtype) for k, v in w.items()} if low else w
        logits, new_aux = forward(wl, aux, img)
        logp = jax.nn.log_softmax(logits)
        ll = jnp.take_along_axis(logp, label[:, None], -1)
        return -jnp.mean(ll), new_aux

    def train_step(w, m, aux, img, label, lr):
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(w, img, label, aux)
        new_w, new_m = {}, {}
        for n in w:
            g = grads[n].astype(w[n].dtype)
            mom = 0.9 * m[n] - lr * g
            new_m[n] = mom
            new_w[n] = w[n] + mom
        return new_w, new_m, new_aux, loss

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    img = jnp.asarray(np.random.rand(batch, 3, image, image), dtype)
    label = jnp.asarray(np.random.randint(0, 1000, batch), jnp.int32)
    return step, w, m, aux, img, label


def _measure_control(step, w, m, aux, img, label, steps):
    """Returns (compile_s, steady img/s) for the pure-JAX control."""
    import jax
    lr = jax.numpy.float32(0.05)
    t0 = time.perf_counter()
    w, m, aux, loss = step(w, m, aux, img, label, lr)
    float(loss)
    compile_s = time.perf_counter() - t0
    w, m, aux, loss = step(w, m, aux, img, label, lr)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        w, m, aux, loss = step(w, m, aux, img, label, lr)
    final = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final), f"control loss diverged: {final}"
    return compile_s, img.shape[0] * steps / dt


def _run_real_data(batch, image, steps, dtype="float32"):
    """Module.fit fed by the REAL input pipeline (ImageRecordIter over a
    synthetic JPEG .rec corpus) — measures end-to-end img/s including
    decode/augment/transfer, the reference's `train_imagenet.py` shape.

    Returns (train_img_s, pipeline_img_s).  The measurement window is
    sized >= 3x the prefetch depth so it cannot be served out of batches
    pre-decoded during the compile of step 0 (round-3's artifact measured
    buffer drain); the standalone pipeline rate is measured on the same
    corpus/settings as the honest input-bound ceiling."""
    import shutil
    import tempfile
    d = tempfile.mkdtemp()
    try:
        return _run_real_data_in(d, batch, image, steps, dtype)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _h2d_probe(batch, image, n_bufs=12):
    """memcpy / blocking / pipelined-ring MB/s — ONE implementation
    shared with the run_io_bench CI gate (tools/bench_io.h2d_probe)."""
    import sys as _sys
    _sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    from bench_io import h2d_probe
    return h2d_probe(batch, image, n_bufs=n_bufs)


_REAL_PREFETCH = 8


def _real_data_iter(rec, batch, image):
    from incubator_mxnet_tpu import io as mxio
    return mxio.ImageRecordIter(
        path_imgrec=rec, data_shape=(3, image, image), batch_size=batch,
        rand_crop=True, rand_mirror=True, shuffle=True,
        mean_r=123.68, mean_g=116.78, mean_b=103.94,
        std_r=58.4, std_g=57.1, std_b=57.4,
        preprocess_threads=4, prefetch_buffer=_REAL_PREFETCH, label_width=1,
        device_augment=True)


def _run_real_data_in(d, batch, image, steps, dtype):
    import incubator_mxnet_tpu as mx
    rec = os.path.join(d, "bench.rec")
    import sys as _sys
    _sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    from bench_io import build_corpus
    warm = _BLOCK
    steps = max(steps, 3 * _REAL_PREFETCH + 2)  # window can't be buffer-fed
    steps = -(-steps // _BLOCK) * _BLOCK        # block-aligned window
    n_img = batch * (warm + steps + _BLOCK)
    build_corpus(rec, n=n_img, size=image + 32)

    # standalone pipeline rate on the same corpus (the input-bound
    # ceiling); window >= 3x prefetch depth, same rule as the training
    # window — a short window would drain pre-decoded batches and
    # overestimate the ceiling
    it = _real_data_iter(rec, batch, image)
    for i, b in enumerate(it):
        if i >= 1:
            break
    t0 = time.perf_counter()
    n = 0
    for i, b in enumerate(it):
        n += batch
        if i >= 3 * _REAL_PREFETCH:
            break
    pipe_img_s = n / (time.perf_counter() - t0)

    mx.random.seed(0)
    mod, ctx = _build_module(
        mx, batch, image, dtype,
        norm=lambda d: it.normalize_symbol(d, dtype=dtype))
    probe = _Probe(warm, steps, batch)
    it.reset()
    mod.fit(it, num_epoch=1,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                              "rescale_grad": 1.0 / batch},
            eval_metric="acc",
            initializer=mx.initializer.Xavier(rnd_type="gaussian",
                                              factor_type="in", magnitude=2),
            batch_end_callback=probe, kvstore=None)
    assert probe.img_s is not None, "real-data probe missed its window"
    return probe.img_s, pipe_img_s


def main():
    batch = int(os.environ.get("BENCH_BATCH", 128))
    image = int(os.environ.get("BENCH_IMAGE", 224))
    steps = int(os.environ.get("BENCH_STEPS", 48))
    steps = -(-steps // _BLOCK) * _BLOCK   # block-aligned probe window
    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    budget = int(os.environ.get("BENCH_BUDGET_S", 480))
    want_control = os.environ.get("BENCH_CONTROL", "1") == "1"
    want_fp32 = os.environ.get("BENCH_FP32", "1") == "1"

    signal.signal(signal.SIGALRM, _alarm)
    signal.signal(signal.SIGTERM, _alarm)
    signal.alarm(budget + 30)
    wd = _watchdog(budget)
    t_start = time.perf_counter()

    def left():
        return budget - (time.perf_counter() - t_start)

    _RESULT.update(batch=batch, image=image, steps=steps, dtype=dtype,
                   api="Module.fit")

    # -- cold-start lane FIRST, before this process touches jax: each
    # probe phase is its own subprocess that must initialize the TPU,
    # which libtpu locks exclusively — a parent already holding the chip
    # would force the probe onto the wrong backend (or fail it)
    if os.environ.get("BENCH_COLDSTART", "1") == "1":
        _RESULT["phase"] = "coldstart"
        try:
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tools"))
            from warmup import coldstart_probe
            probe = coldstart_probe(timeout=max(min(left() - 30, 600), 60))
            for k in ("cold_compile_s", "warm_compile_s", "cold_compiles",
                      "warm_compiles", "warm_cold_ratio", "error"):
                if k in probe:
                    _RESULT[("coldstart_" if k == "error" else "") + k] = \
                        probe[k]
        except Exception as e:
            _RESULT["coldstart_error"] = repr(e)[:200]

    import jax
    # persistent compilation cache: repeat runs skip the multi-minute XLA
    # compile (the cache key covers program + flags + platform)
    cache_dir = os.environ.get("MXNET_COMPILATION_CACHE_DIR",
                               os.path.join(os.path.dirname(
                                   os.path.abspath(__file__)), ".jax_cache"))
    if cache_dir:
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
        except Exception:
            pass
    # unified program cache (compile/): serialized executables keyed by
    # graph-hash x signature x donation x device — a repeat bench run's
    # compile_s records a WARM start (disk hits instead of compiles); the
    # artifact's program_cache block says which one this run was
    prog_cache_dir = os.environ.get(
        "MXNET_PROGRAM_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".mxnet_program_cache"))
    if prog_cache_dir:
        os.environ["MXNET_PROGRAM_CACHE_DIR"] = prog_cache_dir

    # -- framework path (headline dtype) -----------------------------------
    _RESULT["phase"] = f"framework-{dtype}"
    init_s, compile_s, img_s, phases = _run_framework(batch, image, steps,
                                                      dtype)
    _RESULT.update(value=round(img_s, 2),
                   vs_baseline=round(img_s / BASELINE_IMG_S, 3),
                   init_s=round(init_s, 2), compile_s=round(compile_s, 2))
    # per-lane cold-start phase breakdown: framework trace seconds,
    # traced-jaxpr equation count (the graph size XLA compiles — scan
    # dedup shows up here as one layer body per run), and per-program
    # lower vs XLA-compile seconds from the unified cache
    _RESULT["compile_phases"] = {"module": phases}

    # -- guardian overhead probe -------------------------------------------
    # the headline lane above ran with the training guardian ON (its
    # default): the in-graph health word + conditional update must cost
    # <2% — re-measure with the guardian OFF and gate the ratio
    if os.environ.get("BENCH_GUARDIAN", "1") == "1" and left() > 120 and \
            os.environ.get("MXNET_GUARDIAN", "1") not in ("0", "false"):
        # skipped when the user disabled the guardian: the headline lane
        # already ran guardian-off and the probe would measure nothing
        _RESULT["phase"] = f"guardian-off-{dtype}"
        try:
            prev = os.environ.get("MXNET_GUARDIAN")
            os.environ["MXNET_GUARDIAN"] = "0"
            try:
                _, _, img_off, _ = _run_framework(batch, image, steps,
                                                  dtype)
            finally:
                if prev is None:
                    os.environ.pop("MXNET_GUARDIAN", None)
                else:
                    os.environ["MXNET_GUARDIAN"] = prev
            overhead = 1.0 - img_s / img_off if img_off else 0.0
            _RESULT["guardian_off_img_s"] = round(img_off, 2)
            _RESULT["guardian_overhead"] = round(overhead, 4)
            _RESULT["guardian_overhead_ok"] = bool(overhead <= 0.02)
        except Exception as e:
            _RESULT["guardian_error"] = repr(e)[:200]

    # -- pure-JAX control at the same dtype --------------------------------
    if want_control and left() > 90:
        _RESULT["phase"] = f"control-{dtype}"
        try:
            ctl = _pure_jax_resnet50(batch, image, dtype)
            c_compile, c_img_s = _measure_control(*ctl, steps)
            key = "ratio_vs_pure_jax" if dtype == "float32" else \
                "ratio_vs_pure_jax_bf16"
            _RESULT["pure_jax_img_s_" + dtype] = round(c_img_s, 2)
            _RESULT["pure_jax_compile_s"] = round(c_compile, 2)
            _RESULT[key] = round(img_s / c_img_s, 3)
        except Exception as e:  # control failure must not kill the bench
            _RESULT["control_error"] = repr(e)[:200]

    # -- gluon lane (public Estimator loop; fused Gluon step) ---------------
    if os.environ.get("BENCH_GLUON", "1") == "1" and left() > 150:
        _RESULT["phase"] = f"gluon-{dtype}"
        try:
            g_compile, g_img_s, g_phases = _run_gluon(batch, image, steps,
                                                      dtype)
            _RESULT["gluon_img_s"] = round(g_img_s, 2)
            _RESULT["gluon_compile_s"] = round(g_compile, 2)
            _RESULT["gluon_vs_module"] = round(g_img_s / img_s, 3)
            _RESULT.setdefault("compile_phases", {})["gluon"] = g_phases
        except Exception as e:
            _RESULT["gluon_error"] = repr(e)[:200]

    # -- fp32 lane ----------------------------------------------------------
    if want_fp32 and dtype != "float32" and left() > 150:
        _RESULT["phase"] = "framework-float32"
        try:
            _, _, img32, _ = _run_framework(batch, image, steps, "float32")
            _RESULT["fp32_img_s"] = round(img32, 2)
            if want_control:
                ctl = _pure_jax_resnet50(batch, image, "float32")
                _, c32 = _measure_control(*ctl, steps)
                _RESULT["pure_jax_img_s_float32"] = round(c32, 2)
                _RESULT["ratio_vs_pure_jax"] = round(img32 / c32, 3)
        except Exception as e:
            _RESULT["fp32_error"] = repr(e)[:200]

    # -- PTB LSTM lane (BASELINE config #4): tokens/s + raw-JAX control -----
    if os.environ.get("BENCH_LSTM", "1") == "1" and left() > 150:
        _RESULT["phase"] = "lstm"
        try:
            l_compile, tok_s, l_phases = _run_lstm_framework(steps)
            _RESULT["lstm_tokens_s"] = round(tok_s, 1)
            _RESULT["lstm_compile_s"] = round(l_compile, 2)
            _RESULT.setdefault("compile_phases", {})["lstm"] = l_phases
            if want_control and left() > 60:
                _, c_tok_s = _pure_jax_lstm(steps)
                _RESULT["lstm_pure_jax_tokens_s"] = round(c_tok_s, 1)
                _RESULT["lstm_ratio_vs_pure_jax"] = round(tok_s / c_tok_s, 3)
        except Exception as e:
            _RESULT["lstm_error"] = repr(e)[:200]

    # -- real-data lane: the full input pipeline feeds the chip -------------
    if os.environ.get("BENCH_REAL_DATA", "1") == "1" and left() > 180:
        _RESULT["phase"] = "real-data"
        try:
            # h2d three ways: memcpy ceiling, the old BLOCKING device_put
            # baseline, and the pipelined staging-ring rate (io_plane) —
            # says whether this lane is transfer-bound (dev tunnel
            # ~90 MB/s) or pipeline-bound (real host, GB/s PCIe)
            h2d_probe = _h2d_probe(batch, image)
            h2d = h2d_probe["blocking_MBps"]
            _RESULT["h2d_MBps"] = h2d
            _RESULT["h2d_pipelined_MBps"] = h2d_probe["pipelined_MBps"]
            # device-augment pipeline: batches cross as uint8 NHWC (the
            # normalize/cast finish is in-graph), a quarter of fp32 bytes
            from incubator_mxnet_tpu import io_plane as _io_plane
            io_before = _io_plane.stats()
            real, pipe = _run_real_data(batch, image, steps, dtype)
            io_after = _io_plane.stats()
            _RESULT["real_data_img_s"] = round(real, 2)
            _RESULT["io_pipeline_img_s"] = round(pipe, 2)
            base = img_s
            if base:
                _RESULT["real_data_vs_synthetic"] = round(real / base, 3)
            # the io lane: probe numbers + the training run's own ring
            # occupancy/stall evidence (io.* is the obs namespace too)
            fit_batches = io_after["batches"] - io_before["batches"]
            fit_stalls = io_after["stalls"] - io_before["stalls"]
            _RESULT["io"] = {
                **h2d_probe,
                "real_vs_synthetic": round(real / base, 3) if base
                else None,
                "ring_batches": fit_batches,
                "ring_stall_pct": round(100.0 * fit_stalls /
                                        max(fit_batches, 1), 2),
                "ring_stall_s": round(io_after["stall_s"] -
                                      io_before["stall_s"], 4),
                "zero_copy_transfers": io_after["zero_copy"] -
                io_before["zero_copy"],
            }
            if real > 1.15 * max(pipe, 1e-9) and real > 0.9 * (base or real):
                # can't train faster than the pipeline decodes unless the
                # window was fed from the prefetch buffer — flag it
                _RESULT["real_data_buffer_fed"] = True
            # device-augment lane ships uint8 (1 byte/element)
            xfer_img_s = h2d * 1e6 / (3 * image * image)
            if real < 0.8 * pipe and real < 1.5 * xfer_img_s:
                _RESULT["real_data_transfer_bound"] = True
        except Exception as e:
            _RESULT["real_data_error"] = repr(e)[:200]

    # program-cache traffic of THIS run: compiles vs disk hits says
    # whether the headline compile_s above was a cold or a warm start
    try:
        from incubator_mxnet_tpu import compile as _compile
        st = _compile.stats()
        _RESULT["program_cache"] = {
            **{k: st["counters"][k] for k in
               ("compiles", "disk_hits", "stores")},
            "disk_misses": st["counters"].get("disk_misses", 0),
            "lower_s": st["counters"].get("lower_s_total", 0.0),
            "compile_s": st["counters"].get("compile_s_total", 0.0),
            "hit_rate": st["hit_rate"],
        }
        _compile.write_stats()
    except Exception:
        pass

    _RESULT["phase"] = "done"
    signal.alarm(0)
    wd.cancel()
    _emit()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        _RESULT["error"] = repr(e)[:300]
        _emit()
    # hard-exit after the JSON line: PJRT client/tunnel teardown from
    # interpreter shutdown has aborted the process before (rc 134 in
    # BENCH_r03 — "terminate called without an active exception"), and the
    # result is already on stdout
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)
