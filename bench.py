"""Benchmark: ResNet-50 training throughput (img/sec) on one chip.

Baseline (BASELINE.md): reference MXNet ResNet-50 *training* at 363.69
img/sec on V100, batch 128 (`docs/faq/perf.md:205-224`).  The whole train
step — forward, backward, SGD-momentum update, BatchNorm stat updates — is
ONE donated XLA program, the framework's flagship execution path
(hybridized graph → single compiled computation), mirroring the reference
perf harness `example/image-classification/benchmark_score.py`.

Because this environment's chip sits behind an experimental tunnel
(~110 ms round trip per host fetch; absolute V100-class numbers are not
reachable), the bench also runs a HAND-WRITTEN pure-JAX ResNet-50 train
step as a control on the same chip: `ratio_vs_pure_jax` (framework step
time ÷ pure-JAX step time) is the honest framework-overhead metric.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
A SIGALRM watchdog (BENCH_BUDGET_S, default 480 s) emits a partial result
instead of dying silently.

Env overrides: BENCH_BATCH (default 128), BENCH_IMAGE (224), BENCH_STEPS (5),
BENCH_DTYPE (float32), BENCH_BUDGET_S (480), BENCH_CONTROL (1), BENCH_BF16 (1).
"""
from __future__ import annotations

import json
import os
import signal
import sys
import time

import numpy as np

BASELINE_IMG_S = 363.69  # reference ResNet-50 training, V100 bs=128

_RESULT = {
    "metric": "resnet50_train_img_per_sec",
    "value": 0.0,
    "unit": "img/sec/chip",
    "vs_baseline": 0.0,
    "phase": "startup",
}
_EMITTED = False


def _emit():
    global _EMITTED
    if _EMITTED:
        return
    _EMITTED = True
    print(json.dumps(_RESULT), flush=True)


def _alarm(signum, frame):
    _RESULT["partial"] = True
    _emit()
    os._exit(0)


# ---------------------------------------------------------------------------
# Framework path: hybridized Gluon ResNet-50 -> one donated XLA train step
# ---------------------------------------------------------------------------

def build_train_step(batch, image, dtype):
    import jax
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from incubator_mxnet_tpu.symbol.symbol import graph_eval_fn

    mx.random.seed(0)
    # place the model on the accelerator; MXNet semantics default to cpu()
    # (the host device), which on this platform is a different PJRT device —
    # training there would never touch the TPU
    ctx = mx.tpu() if mx.context.num_tpus() else mx.cpu()
    net = resnet50_v1(classes=1000)
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    x = nd.random.uniform(shape=(batch, 3, image, image), ctx=ctx)
    net.hybridize()
    net(x)
    cg = net._cached_graph
    gfn = graph_eval_fn(cg.symbol, True)[0]

    all_params = {p.name: p for p in net.collect_params().values()}
    data_name = cg.data_names[0]
    arg_names = [n for n in cg.arg_names if n != data_name]
    key = jax.random.PRNGKey(0)

    def cast(a):
        return a.astype(dtype) if a.dtype == np.float32 and \
            dtype != "float32" else a

    weights = {n: cast(all_params[n].data()._data) for n in arg_names}
    moms = {n: jnp.zeros_like(w) for n, w in weights.items()}
    auxs = [all_params[n].data()._data for n in cg.aux_names]

    def loss_fn(w, img, label, aux):
        args = tuple(img if n == data_name else w[n] for n in cg.arg_names)
        outs, new_aux = gfn(args, tuple(aux), key)
        logits = outs[0].astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        ll = jnp.take_along_axis(logp, label[:, None], -1)
        return -jnp.mean(ll), new_aux

    def train_step(w, m, aux, img, label, lr):
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(w, img, label, aux)
        new_w = {}
        new_m = {}
        for n in w:
            g = grads[n].astype(w[n].dtype)
            mom = 0.9 * m[n] - lr * g
            new_m[n] = mom
            new_w[n] = w[n] + mom
        return new_w, new_m, list(new_aux), loss

    train_step_d = jax.jit(train_step, donate_argnums=(0, 1, 2))
    img = jnp.asarray(np.random.rand(batch, 3, image, image), dtype)
    label = jnp.asarray(np.random.randint(0, 1000, batch), jnp.int32)
    return train_step_d, weights, moms, auxs, img, label


# ---------------------------------------------------------------------------
# Control path: hand-written raw-JAX ResNet-50 train step (no framework)
# ---------------------------------------------------------------------------

def _pure_jax_resnet50(batch, image, dtype):
    """Raw-JAX ResNet-50 v1 (NCHW, same arch as the framework model):
    conv/bn/relu stem, bottleneck stages [3,4,6,3], SGD momentum, BN
    running stats — everything a performance-minded JAX user would write."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.RandomState(0)
    params, auxs = {}, {}

    def conv_p(name, cin, cout, k):
        fan = (cin * k * k + cout * k * k) / 2.0
        s = np.sqrt(3.0 / fan)
        params[name + ".w"] = rng.uniform(-s, s, (cout, cin, k, k)).astype("f4")

    def bn_p(name, c):
        params[name + ".g"] = np.ones(c, "f4")
        params[name + ".b"] = np.zeros(c, "f4")
        auxs[name + ".mean"] = np.zeros(c, "f4")
        auxs[name + ".var"] = np.ones(c, "f4")

    # stem
    conv_p("stem", 3, 64, 7)
    bn_p("stem", 64)
    layers = [3, 4, 6, 3]
    chans = [(64, 256), (128, 512), (256, 1024), (512, 2048)]
    cin = 64
    for si, (n, (cm, cout)) in enumerate(zip(layers, chans)):
        for bi in range(n):
            p = f"s{si}b{bi}"
            conv_p(p + ".c1", cin if bi == 0 else cout, cm, 1)
            bn_p(p + ".c1", cm)
            conv_p(p + ".c2", cm, cm, 3)
            bn_p(p + ".c2", cm)
            conv_p(p + ".c3", cm, cout, 1)
            bn_p(p + ".c3", cout)
            if bi == 0:
                conv_p(p + ".ds", cin, cout, 1)
                bn_p(p + ".ds", cout)
        cin = cout
    s = np.sqrt(3.0 / ((2048 + 1000) / 2.0))
    params["fc.w"] = rng.uniform(-s, s, (1000, 2048)).astype("f4")
    params["fc.b"] = np.zeros(1000, "f4")

    def conv(x, w, stride=1):
        return lax.conv_general_dilated(
            x, w.astype(x.dtype), (stride, stride), "SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    def bn(x, p, aux, name, new_aux):
        xm = x.astype(jnp.float32)
        mean = xm.mean((0, 2, 3))
        var = xm.var((0, 2, 3))
        new_aux[name + ".mean"] = 0.9 * aux[name + ".mean"] + 0.1 * mean
        new_aux[name + ".var"] = 0.9 * aux[name + ".var"] + 0.1 * var
        inv = jax.lax.rsqrt(var + 1e-5) * p[name + ".g"]
        out = (xm - mean[:, None, None]) * inv[:, None, None] + \
            p[name + ".b"][:, None, None]
        return out.astype(x.dtype)

    def forward(p, aux, x):
        new_aux = {}
        h = conv(x, p["stem.w"], 2)
        h = jax.nn.relu(bn(h, p, aux, "stem", new_aux))
        h = lax.reduce_window(h, -jnp.inf, lax.max, (1, 1, 3, 3),
                              (1, 1, 2, 2), "SAME")
        for si, (n, (cm, cout)) in enumerate(zip(layers, chans)):
            for bi in range(n):
                pre = f"s{si}b{bi}"
                stride = 2 if (bi == 0 and si > 0) else 1
                idn = h
                o = jax.nn.relu(bn(conv(h, p[pre + ".c1.w"], stride),
                                   p, aux, pre + ".c1", new_aux))
                o = jax.nn.relu(bn(conv(o, p[pre + ".c2.w"]),
                                   p, aux, pre + ".c2", new_aux))
                o = bn(conv(o, p[pre + ".c3.w"]), p, aux, pre + ".c3", new_aux)
                if bi == 0:
                    idn = bn(conv(h, p[pre + ".ds.w"], stride),
                             p, aux, pre + ".ds", new_aux)
                h = jax.nn.relu(o + idn)
        h = h.mean((2, 3)).astype(jnp.float32)
        return h @ p["fc.w"].astype(jnp.float32).T + p["fc.b"], new_aux

    def cast(a):
        return a.astype(dtype) if a.dtype == np.float32 and \
            dtype != "float32" else a

    w = {k: jnp.asarray(cast(v)) for k, v in params.items()}
    m = {k: jnp.zeros_like(v) for k, v in w.items()}
    aux = {k: jnp.asarray(v) for k, v in auxs.items()}

    def loss_fn(w, img, label, aux):
        logits, new_aux = forward(w, aux, img)
        logp = jax.nn.log_softmax(logits)
        ll = jnp.take_along_axis(logp, label[:, None], -1)
        return -jnp.mean(ll), new_aux

    def train_step(w, m, aux, img, label, lr):
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(w, img, label, aux)
        new_w, new_m = {}, {}
        for n in w:
            g = grads[n].astype(w[n].dtype)
            mom = 0.9 * m[n] - lr * g
            new_m[n] = mom
            new_w[n] = w[n] + mom
        return new_w, new_m, new_aux, loss

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    img = jnp.asarray(np.random.rand(batch, 3, image, image), dtype)
    label = jnp.asarray(np.random.randint(0, 1000, batch), jnp.int32)
    return step, w, m, aux, img, label


def _measure(step, w, m, aux, img, label, steps):
    """Returns (compile_s, steady img/s). A host fetch of the loss is the
    only reliable sync point on this platform."""
    import jax
    lr = jax.numpy.float32(0.05)
    t0 = time.perf_counter()
    w, m, aux, loss = step(w, m, aux, img, label, lr)
    float(loss)
    compile_s = time.perf_counter() - t0
    # one more warm step outside the timed window
    w, m, aux, loss = step(w, m, aux, img, label, lr)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        w, m, aux, loss = step(w, m, aux, img, label, lr)
    final = float(loss)
    dt = time.perf_counter() - t0
    assert np.isfinite(final), f"loss diverged: {final}"
    batch = img.shape[0]
    return compile_s, batch * steps / dt


def main():
    batch = int(os.environ.get("BENCH_BATCH", 128))
    image = int(os.environ.get("BENCH_IMAGE", 224))
    steps = int(os.environ.get("BENCH_STEPS", 5))
    dtype = os.environ.get("BENCH_DTYPE", "float32")
    budget = int(os.environ.get("BENCH_BUDGET_S", 480))
    want_control = os.environ.get("BENCH_CONTROL", "1") == "1"
    want_bf16 = os.environ.get("BENCH_BF16", "1") == "1"

    signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(budget)
    _RESULT.update(batch=batch, image=image, steps=steps, dtype=dtype)

    import jax  # noqa: F401

    # -- framework path ----------------------------------------------------
    _RESULT["phase"] = "build"
    t0 = time.perf_counter()
    built = build_train_step(batch, image, dtype)
    _RESULT["init_s"] = round(time.perf_counter() - t0, 2)

    _RESULT["phase"] = "framework"
    compile_s, img_s = _measure(*built, steps)
    _RESULT.update(value=round(img_s, 2),
                   vs_baseline=round(img_s / BASELINE_IMG_S, 3),
                   compile_s=round(compile_s, 2))

    # -- pure-JAX control --------------------------------------------------
    if want_control:
        _RESULT["phase"] = "control"
        try:
            ctl = _pure_jax_resnet50(batch, image, dtype)
            c_compile, c_img_s = _measure(*ctl, steps)
            _RESULT["pure_jax_img_s"] = round(c_img_s, 2)
            _RESULT["pure_jax_compile_s"] = round(c_compile, 2)
            _RESULT["ratio_vs_pure_jax"] = round(c_img_s / img_s, 3)
        except Exception as e:  # control failure must not kill the bench
            _RESULT["control_error"] = repr(e)[:200]

    # -- bf16 framework number --------------------------------------------
    if want_bf16 and dtype == "float32":
        _RESULT["phase"] = "bf16"
        try:
            built16 = build_train_step(batch, image, "bfloat16")
            _, img_s16 = _measure(*built16, steps)
            _RESULT["bf16_img_s"] = round(img_s16, 2)
        except Exception as e:
            _RESULT["bf16_error"] = repr(e)[:200]

    _RESULT["phase"] = "done"
    signal.alarm(0)
    _emit()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        _RESULT["error"] = repr(e)[:300]
        _emit()
        sys.exit(0)
