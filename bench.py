"""Benchmark: ResNet-50 training throughput (img/sec) on one chip.

Baseline (BASELINE.md): reference MXNet ResNet-50 *training* at 363.69
img/sec on V100, batch 128 (`docs/faq/perf.md:205-224`).  The whole train
step — forward, backward, SGD-momentum update, BatchNorm stat updates — is
ONE donated XLA program, which is the framework's flagship execution path
(hybridized graph → single compiled computation).

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
Env overrides: BENCH_BATCH (default 128), BENCH_IMAGE (224), BENCH_STEPS (20),
BENCH_DTYPE (float32|bfloat16).
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

BASELINE_IMG_S = 363.69  # reference ResNet-50 training, V100 bs=128


def build_train_step(batch, image, dtype):
    import jax
    import jax.numpy as jnp
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import nd
    from incubator_mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from incubator_mxnet_tpu.symbol.symbol import graph_eval_fn

    mx.random.seed(0)
    net = resnet50_v1(classes=1000)
    net.initialize(mx.initializer.Xavier())
    x = nd.random.uniform(shape=(batch, 3, image, image))
    net.hybridize()
    net(x)
    cg = net._cached_graph
    gfn = graph_eval_fn(cg.symbol, True)[0]

    all_params = {p.name: p for p in net.collect_params().values()}
    data_name = cg.data_names[0]
    arg_names = [n for n in cg.arg_names if n != data_name]
    key = jax.random.PRNGKey(0)

    def cast(a):
        return a.astype(dtype) if a.dtype == np.float32 and \
            dtype != "float32" else a

    weights = {n: cast(all_params[n].data()._data) for n in arg_names}
    moms = {n: jnp.zeros_like(w) for n, w in weights.items()}
    auxs = [all_params[n].data()._data for n in cg.aux_names]

    def loss_fn(w, img, label, aux):
        args = []
        it = iter(cg.arg_names)
        args = tuple(img if n == data_name else w[n] for n in cg.arg_names)
        outs, new_aux = gfn(args, tuple(aux), key)
        logits = outs[0].astype(jnp.float32)
        logp = jax.nn.log_softmax(logits)
        ll = jnp.take_along_axis(logp, label[:, None], -1)
        return -jnp.mean(ll), new_aux

    @jax.jit
    def train_step(w, m, aux, img, label, lr):
        (loss, new_aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(w, img, label, aux)
        new_w = {}
        new_m = {}
        for n in w:
            g = grads[n].astype(w[n].dtype)
            mom = 0.9 * m[n] - lr * g
            new_m[n] = mom
            new_w[n] = w[n] + mom
        return new_w, new_m, list(new_aux), loss

    train_step_d = jax.jit(train_step.__wrapped__, donate_argnums=(0, 1, 2))
    img = jnp.asarray(np.random.rand(batch, 3, image, image), dtype)
    label = jnp.asarray(np.random.randint(0, 1000, batch), jnp.int32)
    return train_step_d, weights, moms, auxs, img, label


def main():
    batch = int(os.environ.get("BENCH_BATCH", 128))
    image = int(os.environ.get("BENCH_IMAGE", 224))
    steps = int(os.environ.get("BENCH_STEPS", 20))
    dtype = os.environ.get("BENCH_DTYPE", "float32")

    import jax
    step, w, m, aux, img, label = build_train_step(batch, image, dtype)
    lr = jax.numpy.float32(0.05)

    # warmup (compile + 2 steady steps)
    for _ in range(3):
        w, m, aux, loss = step(w, m, aux, img, label, lr)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        w, m, aux, loss = step(w, m, aux, img, label, lr)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    img_per_sec = batch * steps / dt
    print(json.dumps({
        "metric": "resnet50_train_img_per_sec",
        "value": round(img_per_sec, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
