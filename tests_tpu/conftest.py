"""TPU-context test lane (reference `tests/python/gpu/test_operator_gpu.py`
pattern: rerun the operator battery on the accelerator and compare against
the CPU context).

Run with `python -m pytest tests_tpu -q` on a machine with a TPU attached.
Unlike `tests/` (which pins everything to a virtual CPU mesh), this lane
keeps the real platform and skips itself when no TPU is present.
"""
import numpy as np
import pytest


def pytest_collection_modifyitems(config, items):
    import incubator_mxnet_tpu as mx
    if mx.context.num_tpus() == 0:
        skip = pytest.mark.skip(reason="no TPU device attached")
        for item in items:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _seeded():
    np.random.seed(0)
    import incubator_mxnet_tpu as mx
    mx.random.seed(0)
    yield
