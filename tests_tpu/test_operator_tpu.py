"""CPU-vs-TPU operator parity via check_consistency
(reference `tests/python/gpu/test_operator_gpu.py`, which re-runs the CPU
operator suite under the GPU context; here every case runs the same symbol
on both contexts and compares outputs AND gradients)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.test_utils import check_consistency, set_default_context


def _ctxs(**shapes):
    return [{"ctx": mx.cpu(), **shapes}, {"ctx": mx.tpu(), **shapes}]


def _strict_matmul():
    """MXU ops ingest bf16 by default (fp32 accumulate) — force full fp32
    inputs for exact parity checks; a separate test documents the default
    precision envelope."""
    import jax
    return jax.default_matmul_precision("highest")


def test_fully_connected():
    data = mx.sym.Variable("data")
    sym = mx.sym.FullyConnected(data, num_hidden=16, name="fc")
    with _strict_matmul():
        check_consistency(sym, _ctxs(data=(8, 12)))


def test_fully_connected_default_mxu_precision():
    """Default MXU precision: bf16 inputs, fp32 accumulation — parity
    within the bf16 envelope (the documented TPU trade)."""
    data = mx.sym.Variable("data")
    sym = mx.sym.FullyConnected(data, num_hidden=16, name="fc")
    check_consistency(sym, _ctxs(data=(8, 12)), tol=0.1)


def test_convolution():
    data = mx.sym.Variable("data")
    sym = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                             name="conv")
    with _strict_matmul():
        check_consistency(sym, _ctxs(data=(2, 3, 10, 10)))


def test_batchnorm_inference():
    data = mx.sym.Variable("data")
    sym = mx.sym.BatchNorm(data, fix_gamma=False, use_global_stats=True,
                           name="bn")
    check_consistency(sym, _ctxs(data=(4, 6, 5, 5)), grad_req="null")


def test_pooling():
    data = mx.sym.Variable("data")
    for pt in ("max", "avg"):
        sym = mx.sym.Pooling(data, kernel=(2, 2), stride=(2, 2),
                             pool_type=pt)
        check_consistency(sym, _ctxs(data=(2, 4, 8, 8)))


def test_activation_softmax():
    data = mx.sym.Variable("data")
    for act in ("relu", "sigmoid", "tanh", "softrelu"):
        check_consistency(mx.sym.Activation(data, act_type=act),
                          _ctxs(data=(6, 10)))
    check_consistency(mx.sym.softmax(data), _ctxs(data=(6, 10)))


def test_elementwise_and_broadcast():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    check_consistency(mx.sym.broadcast_add(a, b),
                      _ctxs(a=(4, 5), b=(1, 5)))
    check_consistency(mx.sym.broadcast_mul(a, b),
                      _ctxs(a=(4, 5), b=(4, 1)))
    with _strict_matmul():
        check_consistency(mx.sym.dot(a, b), _ctxs(a=(4, 6), b=(6, 3)))


def test_reduce_and_shape_ops():
    data = mx.sym.Variable("data")
    check_consistency(mx.sym.sum(data, axis=1), _ctxs(data=(4, 5, 6)))
    check_consistency(mx.sym.mean(data, axis=(0, 2)), _ctxs(data=(4, 5, 6)))
    check_consistency(mx.sym.transpose(data, axes=(1, 0, 2)),
                      _ctxs(data=(3, 4, 5)))
    check_consistency(mx.sym.Reshape(data, shape=(6, -1)),
                      _ctxs(data=(3, 4, 5)))


def test_embedding_layernorm():
    data = mx.sym.Variable("data")
    emb = mx.sym.Embedding(data, input_dim=20, output_dim=8)
    ctxs = [{"ctx": mx.cpu(), "data": (4, 6),
             "type_dict": {"data": np.int32}},
            {"ctx": mx.tpu(), "data": (4, 6),
             "type_dict": {"data": np.int32}}]
    check_consistency(emb, ctxs, grad_req="null")
    check_consistency(mx.sym.LayerNorm(mx.sym.Variable("x")),
                      _ctxs(x=(4, 10)))


def test_gluon_block_on_tpu():
    """High-level flow under the TPU default context (the reference reruns
    entire suites this way; one representative training step here)."""
    from incubator_mxnet_tpu import autograd, nd, gluon
    set_default_context(mx.tpu())
    try:
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu"),
                gluon.nn.Dense(4))
        net.initialize(mx.initializer.Xavier(), ctx=mx.tpu())
        net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        x = nd.random.uniform(shape=(8, 10), ctx=mx.tpu())
        y = nd.zeros((8,), ctx=mx.tpu())
        with autograd.record():
            out = net(x)
            loss = gluon.loss.SoftmaxCrossEntropyLoss()(out, y)
        loss.backward()
        trainer.step(8)
        assert np.isfinite(loss.asnumpy()).all()
        assert out.context.device_type in ("tpu", "gpu")
    finally:
        set_default_context(mx.cpu())
