"""Flash-attention Pallas kernel vs plain-XLA attention on the REAL chip.

VERDICT round-2 item 6 'done' criterion: a Pallas kernel that measurably
BEATS the plain-XLA formulation of the same computation.  The causal
long-sequence case is the structural win: the kernel streams KV blocks
through VMEM with a dynamic loop bound that never executes
above-diagonal blocks and only masks diagonal-touching ones, while the
plain path materializes and masks all T x T scores in HBM.

Timing methodology for this tunnel-fronted chip: iterations are CHAINED
(each step's output feeds the next call) and the sync point is a value
fetch — `block_until_ready` alone under-reports on the tunnel transport.
"""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _naive(q, k, v):
    d = q.shape[-1]
    T = q.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _bench(fn, q, k, v, iters=10, reps=3):
    out = fn(q, k, v)
    float(out[0, 0, 0, 0].astype(jnp.float32))    # warm + sync
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        o = q
        for _ in range(iters):
            o = fn(o, k, v)                        # chained: no overlap
        float(o[0, 0, 0, 0].astype(jnp.float32))   # value fetch = real sync
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def test_flash_attention_beats_xla_long_seq():
    from incubator_mxnet_tpu.ops.flash_attention import flash_attention

    B, T, H, D = 2, 8192, 8, 64
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32) * 0.05,
                             jnp.bfloat16)
    q, k, v = mk(), mk(), mk()

    flash = jax.jit(lambda q, k, v: flash_attention(q, k, v, True, 512, 512))
    naive = jax.jit(_naive)

    # correctness on-chip first
    np.testing.assert_allclose(
        np.asarray(flash(q, k, v), np.float32),
        np.asarray(naive(q, k, v), np.float32), rtol=5e-2, atol=5e-2)

    t_flash = _bench(flash, q, k, v)
    t_naive = _bench(naive, q, k, v)
    speedup = t_naive / t_flash
    print(f"\nflash {t_flash*1e3:.2f} ms vs plain XLA {t_naive*1e3:.2f} ms "
          f"-> {speedup:.2f}x at causal T={T}")
    assert speedup >= 1.15, (
        f"Pallas flash attention must beat plain XLA by >=1.15x, got "
        f"{speedup:.2f}x ({t_flash*1e3:.1f}ms vs {t_naive*1e3:.1f}ms)")
