"""Flash-attention Pallas kernel vs plain-XLA attention on the REAL chip.

VERDICT round-2 item 6 'done' criterion: a Pallas kernel that measurably
BEATS the plain-XLA formulation of the same computation.  The causal
long-sequence case is the structural win: the kernel streams KV blocks
through VMEM with a dynamic loop bound that never executes
above-diagonal blocks and only masks diagonal-touching ones, while the
plain path materializes and masks all T x T scores in HBM.

Timing methodology for this tunnel-fronted chip: iterations are CHAINED
(each step's output feeds the next call) and the sync point is a value
fetch — `block_until_ready` alone under-reports on the tunnel transport.
"""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def _naive(q, k, v):
    d = q.shape[-1]
    T = q.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    mask = jnp.arange(T)[:, None] >= jnp.arange(T)[None, :]
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _bench(fn, q, k, v, iters=10, reps=3):
    out = fn(q, k, v)
    float(out[0, 0, 0, 0].astype(jnp.float32))    # warm + sync
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        o = q
        for _ in range(iters):
            o = fn(o, k, v)                        # chained: no overlap
        float(o[0, 0, 0, 0].astype(jnp.float32))   # value fetch = real sync
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def test_flash_attention_beats_xla_long_seq():
    from incubator_mxnet_tpu.ops.flash_attention import flash_attention

    B, T, H, D = 2, 8192, 8, 64
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32) * 0.05,
                             jnp.bfloat16)
    q, k, v = mk(), mk(), mk()

    flash = jax.jit(lambda q, k, v: flash_attention(q, k, v, True, 512, 512))
    naive = jax.jit(_naive)

    # correctness on-chip first
    np.testing.assert_allclose(
        np.asarray(flash(q, k, v), np.float32),
        np.asarray(naive(q, k, v), np.float32), rtol=5e-2, atol=5e-2)

    t_flash = _bench(flash, q, k, v)
    t_naive = _bench(naive, q, k, v)
    speedup = t_naive / t_flash
    print(f"\nflash {t_flash*1e3:.2f} ms vs plain XLA {t_naive*1e3:.2f} ms "
          f"-> {speedup:.2f}x at causal T={T}")
    assert speedup >= 1.15, (
        f"Pallas flash attention must beat plain XLA by >=1.15x, got "
        f"{speedup:.2f}x ({t_flash*1e3:.1f}ms vs {t_naive*1e3:.1f}ms)")


def test_flash_attention_long_context_streams_kv():
    """T=32k causal on chip: past the VMEM budget the kernel streams KV
    tiles through the grid (flash_attention.py _fwd_kernel_stream), so
    kv_len is bounded by HBM, not VMEM.  Parity is checked against the
    whole-KV kernel on the largest config that still fits VMEM, and the
    32k run must produce finite, mass-conserving softmax sums."""
    import os
    from incubator_mxnet_tpu.ops.flash_attention import (
        flash_attention_partial, _vmem_budget_bytes)

    B, H, D = 1, 1, 64
    rng = np.random.RandomState(1)

    # parity: same shape through both kernels (force streaming via budget)
    T = 4096
    mk = lambda t: jnp.asarray(rng.randn(B, t, H, D).astype("f4") * 0.05,
                               jnp.bfloat16)
    q, k, v = mk(T), mk(T), mk(T)
    o_whole, m_w, l_w = flash_attention_partial(q, k, v, 0, 0, True)
    os.environ["MXNET_FLASH_VMEM_MB"] = "0.1"
    try:
        o_stream, m_s, l_s = flash_attention_partial(q, k, v, 0, 0, True)
    finally:
        del os.environ["MXNET_FLASH_VMEM_MB"]
    np.testing.assert_allclose(np.asarray(l_w), np.asarray(l_s),
                               rtol=2e-3)
    np.testing.assert_allclose(
        np.asarray(o_whole, dtype=np.float32),
        np.asarray(o_stream, dtype=np.float32), rtol=2e-2, atol=2e-2)

    # envelope: T=32k causal through the STREAMING kernel (at D=64 bf16
    # the K+V footprint is 8.4 MB — under the default 10 MB budget — so
    # pin the budget down to guarantee the streaming path runs; D>=128
    # heads would exceed the default budget naturally)
    T = 32768
    q, k, v = mk(T), mk(T), mk(T)
    os.environ["MXNET_FLASH_VMEM_MB"] = "4"
    try:
        assert 2 * T * D * 2 > _vmem_budget_bytes(), \
            "budget must force streaming"
        o, m, l = flash_attention_partial(q, k, v, 0, 0, True)
    finally:
        del os.environ["MXNET_FLASH_VMEM_MB"]
    l_host = np.asarray(l)
    assert np.isfinite(l_host).all()
    # causal row i attends to i+1 keys: sumexp >= 1 (the diagonal term)
    assert (l_host >= 0.99).all()
    o_host = np.asarray(o[0, -1, 0].astype(jnp.float32))
    assert np.isfinite(o_host).all()
