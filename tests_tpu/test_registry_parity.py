"""CPU-vs-TPU parity battery over the ENTIRE operator registry.

Reference pattern: `tests/python/gpu/test_operator_gpu.py` imports the
whole CPU operator suite and reruns it under the GPU context.  Here the
registry itself is the source of truth: every distinct operator is either

  * exercised through `check_consistency` (outputs AND gradients compared
    between mx.cpu() and mx.tpu() with per-dtype tolerances), via an
    auto-generated generic case or an entry in CASES, or
  * listed in SKIP with the triage reason,

and a completeness guard fails the suite if a newly-registered operator is
neither — new ops must be triaged into the parity lane.

Matmul-bearing ops run under `jax.default_matmul_precision("highest")`:
the MXU's default bf16 ingestion is a documented precision envelope tested
separately (`test_operator_tpu.py`), not a parity bug.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym as S
from incubator_mxnet_tpu.ops import registry as _reg
from incubator_mxnet_tpu.test_utils import check_consistency


def _case(shapes, grad_req="write", tol=None, data_scale=1.0, **params):
    return {"shapes": shapes, "grad_req": grad_req, "tol": tol,
            "scale": data_scale, "params": params}


V = (3, 4)          # generic vector-ish input
M = (4, 4)          # square matrix (linalg)
IMG = (2, 3, 8, 8)  # NCHW image
SEQ = (5, 3, 6)     # TNC sequence

# -- explicit cases for ops the generic profile can't drive -----------------
CASES = {
    # heads / NN layers
    "Activation": _case({"data": V}, act_type="relu"),
    "Cast": _case({"data": V}, dtype="float64"),
    "Embedding": _case({"data": None}, grad_req="null"),  # built below
    "LRN": _case({"data": IMG}, nsize=3),
    "Pad": _case({"data": IMG}, mode="constant",
                 pad_width=(0, 0, 0, 0, 1, 1, 2, 2)),
    "SliceChannel": _case({"data": (4, 6)}, num_outputs=2),
    "GridGenerator": _case({"data": (2, 6)}, transform_type="affine",
                           target_shape=(8, 8)),
    "ROIPooling": _case({"data": IMG, "rois": (2, 5)}, grad_req="null",
                        pooled_size=(2, 2), spatial_scale=1.0),
    "_contrib_ROIAlign": _case({"data": IMG, "rois": (2, 5)},
                               grad_req="null", pooled_size=(2, 2),
                               spatial_scale=1.0),
    "Convolution": _case({"data": IMG}, kernel=(3, 3), num_filter=4,
                         pad=(1, 1)),
    "Deconvolution": _case({"data": IMG}, kernel=(3, 3), num_filter=4),
    "FullyConnected": _case({"data": (4, 6)}, num_hidden=5),
    "Concat": _case({"arg0": V, "arg1": V}, num_args=2, dim=1),
    "add_n": _case({"arg0": V, "arg1": V}, num_args=2),
    "stack": _case({"arg0": V, "arg1": V}, num_args=2),
    "LeakyReLU": _case({"data": V}, act_type="leaky"),
    "UpSampling": _case({"arg0": IMG}, num_args=1, scale=2,
                        sample_type="nearest"),
    "Crop": _case({"arg0": IMG}, num_args=1, h_w=(5, 5)),
    "SequenceLast": _case({"data": SEQ}),
    "SequenceMask": _case({"data": SEQ}),
    "SequenceReverse": _case({"data": SEQ}),
    "ctc_loss": _case({"data": (6, 2, 5), "label": (2, 3)},
                      grad_req="null"),
    "BatchNorm": _case({"data": IMG}, grad_req="null",
                       use_global_stats=True, fix_gamma=False),
    "_contrib_SyncBatchNorm": _case({"data": IMG}, grad_req="null",
                                    use_global_stats=True, fix_gamma=False,
                                    key="bn0"),
    "cast_storage": _case({"data": V}, stype="default"),
    # built by name in _run_case (structured inputs / subgraph attrs)
    "khatri_rao": _case({"data": None}),
    "_histogram": _case({"data": None}),
    "_ravel_multi_index": _case({"data": None}),
    "_unravel_index": _case({"data": None}),
    "_contrib_count_sketch": _case({"data": None}),
    "_foreach": _case({"data": None}),
    "_while_loop": _case({"data": None}),
    "_cond": _case({"data": None}),
    "_contrib_DeformableConvolution": _case(
        {"data": IMG, "offset": (2, 18, 6, 6)}, kernel=(3, 3),
        num_filter=4, tol=5e-3),
    "_contrib_DeformablePSROIPooling": _case(
        {"data": (2, 8, 8, 8), "rois": (2, 5), "trans": (2, 2, 2, 2)},
        grad_req="null", spatial_scale=1.0, output_dim=2, group_size=2,
        pooled_size=2, part_size=2, sample_per_part=2, trans_std=0.1),
    "LayerNorm": _case({"data": (4, 6)}),
    "topk": _case({"data": (4, 6)}, grad_req="null", k=2),
    # scalar-op family: one representative shape, scalar=2.5
    **{n: _case({"data": V}, scalar=2.5) for n in (
        "_div_scalar", "_maximum_scalar", "_minimum_scalar",
        "_minus_scalar", "_mul_scalar", "_plus_scalar", "_rdiv_scalar",
        "_rminus_scalar")},
    **{n: _case({"data": V}, grad_req="null", scalar=2.5) for n in (
        "_equal_scalar", "_greater_equal_scalar", "_greater_scalar",
        "_lesser_equal_scalar", "_lesser_scalar", "_logical_and_scalar",
        "_logical_or_scalar", "_logical_xor_scalar",
        "_not_equal_scalar")},
    "_mod_scalar": _case({"data": V}, grad_req="null", scalar=2.5),
    "_rmod_scalar": _case({"data": V}, grad_req="null", scalar=2.5),
    "_hypot_scalar": _case({"data": V}, scalar=2.5),
    "_power_scalar": _case({"data": V}, grad_req="null", scalar=2.0),
    "_rpower_scalar": _case({"data": V}, grad_req="null", scalar=2.0),
    # shape/index manipulation
    "broadcast_to": _case({"data": (1, 4)}, shape=(3, 4)),
    "Reshape": _case({"data": V}, shape=(4, 3)),
    "_contrib_MultiBoxPrior": _case({"data": IMG}, grad_req="null",
                                    sizes=(0.5, 0.25), ratios=(1.0, 2.0)),
    "_contrib_BilinearResize2D": _case({"data": IMG}, height=4, width=4),
    "expand_dims": _case({"data": V}, axis=1),
    "one_hot": _case({"data": None}, grad_req="null"),  # built below
    "repeat": _case({"data": V}, repeats=2),
    "reverse": _case({"data": V}, axis=0),
    "tile": _case({"data": V}, reps=(2, 1)),
    "slice": _case({"data": V}, begin=(0, 1), end=(2, 3)),
    "slice_axis": _case({"data": V}, axis=1, begin=0, end=2),
    "depth_to_space": _case({"data": (1, 4, 3, 3)}, block_size=2),
    "space_to_depth": _case({"data": (1, 1, 4, 4)}, block_size=2),
    "_eye": _case({}, grad_req="null", N=4),
    "_full": _case({}, grad_req="null", shape=(2, 3), value=1.5),
    "_linspace": _case({}, grad_req="null", start=0.0, stop=1.0, num=7),
    "_contrib_interleaved_matmul_selfatt_qk": _case(
        {"queries_keys_values": (4, 2, 18)}, heads=2),
    "_contrib_interleaved_matmul_selfatt_valatt": _case(
        {"queries_keys_values": (4, 2, 18), "attention": (4, 4, 4)},
        heads=2),
}

# -- triaged exclusions ------------------------------------------------------
SKIP = {
    # int8 lane: covered by tests/test_quantization.py end-to-end; the
    # int domain makes gradient parity meaningless
    "_contrib_quantize": "int8 lane; covered in test_quantization.py",
    "_contrib_quantize_v2": "int8 lane",
    "_contrib_requantize": "int8 lane",
    "_contrib_quantized_conv": "int8 lane",
    "_contrib_quantized_fully_connected": "int8 lane",
    "_contrib_quantized_pooling": "int8 lane",
    "_sg_pallas_fc_relu": "subgraph-internal fused op; tested in "
                          "test_subgraph.py",
    "_index": "indexing helper with data-dependent shapes (host-side)",
    "scatter_nd": "integer index inputs; covered in test_ndarray.py",
    "_contrib_bipartite_matching": "host-side greedy matching; covered in "
                                   "test_image_detection.py",
    "_contrib_MultiBoxTarget": "detection target assembly; covered in "
                               "test_image_detection.py",
    "_contrib_MultiBoxDetection": "nms/decode pipeline needing structured "
                                  "(cls_prob, loc_pred, anchor) inputs; "
                                  "covered in test_image_detection.py",
    "linalg_syevd": "eigenvector sign/ordering is backend-defined; "
                    "reconstruction-based checks live in test_operator.py",
    "linalg_gelqf": "LQ factor signs are backend-defined; reconstruction "
                    "checks live in test_operator.py",
    # RNG family: same threefry key chain on both devices, but the op
    # consumes the GLOBAL key singleton — covered by seeded-moments tests
    # in tests/test_operator.py; cross-device parity is by construction
    # (counter-based threefry is device-independent)
    **{n: "rng op; counter-based threefry is device-independent by design"
       for n in ("Dropout", "RNN", "_random_exponential", "_random_gamma",
                 "_random_generalized_negative_binomial",
                 "_random_negative_binomial", "_random_normal",
                 "_random_poisson", "_random_randint", "_random_uniform",
                 "_sample_gamma", "_sample_multinomial", "_sample_normal",
                 "_sample_uniform", "_shuffle")},
}

# generic ops that need a domain/shape tweak
TWEAKS = {
    "log": dict(use_abs=True), "log10": dict(use_abs=True),
    "log2": dict(use_abs=True), "sqrt": dict(use_abs=True),
    "rsqrt": dict(use_abs=True), "log1p": dict(use_abs=True),
    "cbrt": dict(use_abs=True), "rcbrt": dict(use_abs=True),
    "reciprocal": dict(use_abs=True),
    "gamma": dict(use_abs=True), "gammaln": dict(use_abs=True),
    "arccosh": dict(shift=2.0),
    "erfinv": dict(scale=0.3),
    "InstanceNorm": dict(shapes={"data": IMG}),
    "_contrib_AdaptiveAvgPooling2D": dict(shapes={"data": IMG},
                                          params={"output_size": (2, 2)}),
    "broadcast_power": dict(use_abs=True),
    "arcsin": dict(scale=0.3), "arccos": dict(scale=0.3),
    "arctanh": dict(scale=0.3),
    "Pooling": dict(shapes={"data": IMG}),
    "Pooling_v1": dict(shapes={"data": IMG}),
    "BilinearSampler": dict(shapes={"data": IMG, "grid": (2, 2, 8, 8)},
                            scale=0.5),
    "SpatialTransformer": dict(shapes={"data": IMG, "loc": (2, 6)},
                               params={"transform_type": "affine",
                                       "sampler_type": "bilinear",
                                       "target_shape": (8, 8)}),
    "Correlation": dict(shapes={"data1": IMG, "data2": IMG},
                        grad_req="null"),
    "batch_dot": dict(shapes={"lhs": (2, 3, 4), "rhs": (2, 4, 5)}),
    "dot": dict(shapes={"lhs": (3, 4), "rhs": (4, 5)}),
    "linalg_gemm": dict(shapes={"A": M, "B": M, "C": M}),
    "linalg_gemm2": dict(shapes={"A": M, "B": M}),
    "linalg_potrf": dict(shapes={"A": M}, spd=True, grad_req="null"),
    "linalg_potri": dict(shapes={"A": M}, spd=True, grad_req="null"),
    "linalg_trsm": dict(shapes={"A": M, "B": M}, spd=True,
                        grad_req="null"),
    "linalg_trmm": dict(shapes={"A": M, "B": M}, spd=True,
                        grad_req="null"),
    "linalg_sumlogdiag": dict(shapes={"A": M}, spd=True, grad_req="null"),
    "linalg_syrk": dict(shapes={"A": M}, grad_req="null"),
    "linalg_slogdet": dict(shapes={"A": M}, spd=True, grad_req="null"),
    "linalg_extractdiag": dict(shapes={"A": M}),
    "linalg_makediag": dict(shapes={"A": (4,)}),
    "linalg_extracttrian": dict(shapes={"A": M}),
    "linalg_maketrian": dict(shapes={"A": (10,)}),
    "linalg_inverse": dict(shapes={"A": M}, spd=True, grad_req="null"),
    "linalg_det": dict(shapes={"A": M}, spd=True, grad_req="null"),
    "SVMOutput": dict(shapes={"data": (4, 5), "label": (4,)},
                      grad_req="null"),
    "SoftmaxOutput": dict(shapes={"data": (4, 5),
                                  "softmax_label": (4,)},
                          grad_req="null"),
}


def _distinct_ops():
    seen = {}
    for name in _reg.list_ops():
        op = _reg.get(name)
        seen.setdefault(op.name, op)
    return seen


def _strict_matmul():
    import jax
    return jax.default_matmul_precision("highest")


def _generic_names():
    from incubator_mxnet_tpu.ops.registry import REQUIRED
    out = []
    for n, op in sorted(_distinct_ops().items()):
        if n in CASES or n in SKIP:
            continue
        if n.startswith("_grad_of_") or n.startswith("_cached_op"):
            # derived ops materialize lazily while earlier tests run
            # (create_graph gradients; hybridize() CachedOp wrappers);
            # they are internal wrappers of already-triaged base ops and
            # user graphs, not public surface
            continue
        req = [k for k, v in op.params.items() if v is REQUIRED]
        if op.needs_rng or op.nin < 0 or req:
            out.append((n, "unhandled"))
        else:
            out.append((n, "generic"))
    return out


def test_registry_fully_triaged():
    """Every registered op is a case, a generic, or a documented skip."""
    unhandled = [n for n, kind in _generic_names() if kind == "unhandled"]
    assert not unhandled, (
        "ops neither cased nor skipped (triage them into CASES or SKIP): "
        f"{unhandled}")


def _run_case(name):
    op = _reg.get(name)
    case = CASES.get(name)
    tweak = TWEAKS.get(name, {})
    grad_req = (case or {}).get("grad_req", tweak.get("grad_req", "write"))
    tol = (case or {}).get("tol") or 1e-3
    params = dict((case or {}).get("params", tweak.get("params", {})))

    if name == "khatri_rao":
        s = S.khatri_rao(S.Variable("a"), S.Variable("b"))
        ctxs = [{"ctx": mx.cpu(), "a": (2, 3), "b": (4, 3)},
                {"ctx": mx.tpu(), "a": (2, 3), "b": (4, 3)}]
        check_consistency(s, ctxs, grad_req="write")
        return
    if name == "_histogram":
        s = S.Group(list(S.histogram(S.Variable("data"), bin_cnt=5,
                                     range=(-2, 2))))
        ctxs = [{"ctx": mx.cpu(), "data": (40,)},
                {"ctx": mx.tpu(), "data": (40,)}]
        check_consistency(s, ctxs, grad_req="null")
        return
    if name in ("_ravel_multi_index", "_unravel_index"):
        if name == "_unravel_index":
            s = S.unravel_index(S.Variable("data"), shape=(3, 4))
            idx = np.random.randint(0, 12, (6,)).astype("f4")
            shapes = {"data": (6,)}
        else:
            s = S.ravel_multi_index(S.Variable("data"), shape=(3, 4))
            idx = np.stack([np.random.randint(0, 3, 6),
                            np.random.randint(0, 4, 6)]).astype("f4")
            shapes = {"data": (2, 6)}
        ctxs = [dict(shapes, ctx=mx.cpu()), dict(shapes, ctx=mx.tpu())]
        check_consistency(s, ctxs, grad_req="null",
                          arg_params={"data": idx})
        return
    if name == "_contrib_count_sketch":
        s = S.contrib.count_sketch(S.Variable("data"), S.Variable("h"),
                                   S.Variable("s"), out_dim=5)
        h = np.random.randint(0, 5, (8,)).astype("f4")
        sg = np.random.choice([-1.0, 1.0], 8).astype("f4")
        shapes = {"data": (3, 8), "h": (8,), "s": (8,)}
        ctxs = [dict(shapes, ctx=mx.cpu()), dict(shapes, ctx=mx.tpu())]
        check_consistency(s, ctxs, grad_req="null",
                          arg_params={"h": h, "s": sg})
        return
    if name == "_foreach":
        w = S.Variable("w")
        outs, st = S.contrib.foreach(
            lambda x, st_: (S.broadcast_mul(x, w) + st_,
                            S.broadcast_mul(x, w) + st_),
            S.Variable("data"), S.Variable("init"))
        s = S.Group([outs, st])
        shapes = {"data": (5, 4), "init": (4,), "w": (4,)}
        ctxs = [dict(shapes, ctx=mx.cpu()), dict(shapes, ctx=mx.tpu())]
        check_consistency(s, ctxs, grad_req="write")
        return
    if name == "_while_loop":
        outs, fin = S.contrib.while_loop(
            cond=lambda i, acc: i < 4,
            func=lambda i, acc: ([acc + i], [i + 1, acc + i]),
            loop_vars=[S.Variable("i0"), S.Variable("acc0")],
            max_iterations=6)
        s = S.Group(list(outs) + list(fin))
        shapes = {"i0": (1,), "acc0": (3,)}
        ctxs = [dict(shapes, ctx=mx.cpu()), dict(shapes, ctx=mx.tpu())]
        check_consistency(s, ctxs, grad_req="null",
                          arg_params={"i0": np.zeros(1, "f4")})
        return
    if name == "_cond":
        a = S.Variable("a")
        b = S.Variable("b")
        s = S.contrib.cond(S.sum(a) < 1.0,
                           lambda: (a + 5) * (b + 5),
                           lambda: (a - 5) * (b - 5))
        shapes = {"a": (3,), "b": (3,)}
        ctxs = [dict(shapes, ctx=mx.cpu()), dict(shapes, ctx=mx.tpu())]
        check_consistency(s, ctxs, grad_req="null")
        return
    if name == "Embedding":
        data = S.Variable("data")
        s = S.Embedding(data, input_dim=10, output_dim=4, name="emb")
        idx = np.random.randint(0, 10, (6,)).astype("f4")
        ctxs = [{"ctx": mx.cpu(), "data": (6,)},
                {"ctx": mx.tpu(), "data": (6,)}]
        check_consistency(s, ctxs, grad_req="null",
                          arg_params={"data": idx})
        return
    if name == "one_hot":
        data = S.Variable("data")
        s = S.one_hot(data, depth=5)
        idx = np.random.randint(0, 5, (6,)).astype("f4")
        ctxs = [{"ctx": mx.cpu(), "data": (6,)},
                {"ctx": mx.tpu(), "data": (6,)}]
        check_consistency(s, ctxs, grad_req="null",
                          arg_params={"data": idx})
        return
    if name == "pick":
        s = S.pick(S.Variable("data"), S.Variable("index"))
        idx = np.random.randint(0, 5, (6,)).astype("f4")
        ctxs = [{"ctx": mx.cpu(), "data": (6, 5), "index": (6,)},
                {"ctx": mx.tpu(), "data": (6, 5), "index": (6,)}]
        check_consistency(s, ctxs, grad_req="null",
                          arg_params={"index": idx})
        return
    if name == "batch_take":
        s = S.batch_take(S.Variable("data"), S.Variable("indices"))
        idx = np.random.randint(0, 5, (6,)).astype("f4")
        ctxs = [{"ctx": mx.cpu(), "data": (6, 5), "indices": (6,)},
                {"ctx": mx.tpu(), "data": (6, 5), "indices": (6,)}]
        check_consistency(s, ctxs, grad_req="null",
                          arg_params={"indices": idx})
        return
    if name == "_contrib_box_iou":
        s = getattr(S, "_internal")._contrib_box_iou(
            S.Variable("lhs"), S.Variable("rhs"))
        rng = np.random.RandomState(0)
        mk = lambda n: np.sort(rng.rand(n, 2, 2), axis=1) \
            .reshape(n, 4).astype("f4")  # valid (xmin, ymin, xmax, ymax)
        ctxs = [{"ctx": mx.cpu(), "lhs": (3, 4), "rhs": (5, 4)},
                {"ctx": mx.tpu(), "lhs": (3, 4), "rhs": (5, 4)}]
        check_consistency(s, ctxs, grad_req="null",
                          arg_params={"lhs": mk(3), "rhs": mk(5)})
        return
    if name == "_contrib_index_copy":
        s = getattr(S, "_internal")._contrib_index_copy(
            S.Variable("data"), S.Variable("index"), S.Variable("new"))
        idx = np.array([0, 2], "f4")
        ctxs = [{"ctx": mx.cpu(), "data": (4, 3), "index": (2,),
                 "new": (2, 3)},
                {"ctx": mx.tpu(), "data": (4, 3), "index": (2,),
                 "new": (2, 3)}]
        check_consistency(s, ctxs, grad_req="null",
                          arg_params={"index": idx})
        return
    if name == "gather_nd":
        s = S.gather_nd(S.Variable("data"), S.Variable("indices"))
        idx = np.random.randint(0, 4, (2, 5)).astype("f4")
        ctxs = [{"ctx": mx.cpu(), "data": (4, 4), "indices": (2, 5)},
                {"ctx": mx.tpu(), "data": (4, 4), "indices": (2, 5)}]
        check_consistency(s, ctxs, grad_req="null",
                          arg_params={"indices": idx})
        return

    if case is not None:
        shapes = dict(case["shapes"])
    else:
        shapes = dict(tweak.get("shapes") or {})
        if not shapes:
            nin = op.num_inputs({})
            in_names = op.list_input_names(params) or \
                [f"arg{i}" for i in range(nin)]
            shapes = {in_names[i] if i else
                      ("data" if in_names[0] in (None, "data") else
                       in_names[0]): V for i in range(max(nin, 0))}

    scale = (case or {}).get("scale", tweak.get("scale", 1.0))
    spd = tweak.get("spd", False)
    shift = tweak.get("shift", 0.0)
    use_abs = tweak.get("use_abs", False)

    # build the symbol: one Variable per input slot
    in_names = op.list_input_names(params) or list(shapes)
    vars_ = [S.Variable(n) for n in (in_names if in_names else list(shapes))]
    fn = getattr(S, name, None) or getattr(S._internal, name)
    if op.nin == 0 or not shapes:
        s = fn(**params)
        check_consistency(s, [{"ctx": mx.cpu()}, {"ctx": mx.tpu()}],
                          grad_req="null", tol=tol)
        return
    s = fn(*vars_, **params)

    arg_params = None
    if spd:
        a = np.random.normal(size=M)
        spd_mat = a @ a.T + 4 * np.eye(M[0])
        arg_params = {list(shapes)[0]: spd_mat}
        for extra in list(shapes)[1:]:
            arg_params[extra] = np.random.normal(size=shapes[extra])
    elif use_abs or shift:
        arg_params = {k: np.abs(np.random.normal(scale=scale, size=v)) +
                      shift + (0.1 if use_abs else 0.0)
                      for k, v in shapes.items()}

    ctxs = [dict(shapes, ctx=mx.cpu()), dict(shapes, ctx=mx.tpu())]
    with _strict_matmul():
        check_consistency(s, ctxs, grad_req=grad_req, tol=tol, scale=scale,
                          arg_params=arg_params)


ALL_NAMES = sorted(set(list(_distinct_ops())) - set(SKIP))

# optional sharding for slow single-chip runs: MXNET_PARITY_SHARD="i/n"
import os as _os
_shard = _os.environ.get("MXNET_PARITY_SHARD")
if _shard:
    _i, _n = (int(x) for x in _shard.split("/"))
    ALL_NAMES = ALL_NAMES[_i::_n]


@pytest.mark.parametrize("name", ALL_NAMES)
def test_op_parity(name):
    _run_case(name)
